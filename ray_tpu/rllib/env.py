"""Environment API + built-in vectorized benchmark envs.

Reference: rllib/env/ (EnvRunner wraps gymnasium vector envs;
rllib/examples/envs has the classic-control tasks). No gymnasium in
this image, so CartPole and Pendulum are implemented here directly as
*batched numpy* dynamics — the whole vector steps in one ufunc pass,
which is both faster than a Python loop over envs and mirrors how a
TPU-resident env would batch.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .spaces import Box, Discrete

_ENV_REGISTRY: Dict[str, Callable[..., "Env"]] = {}


def register_env(name: str, creator: Callable[..., "Env"]) -> None:
    """Reference: ray.tune.register_env — name -> creator for configs."""
    _ENV_REGISTRY[name] = creator


def make_env(name: str, **kwargs) -> "Env":
    if name in _ENV_REGISTRY:
        return _ENV_REGISTRY[name](**kwargs)
    raise KeyError(
        f"unknown env {name!r}; registered: {sorted(_ENV_REGISTRY)}"
    )


class Env:
    """Single-env API (gymnasium-shaped: reset/step, 5-tuple step)."""

    observation_space: Box
    action_space: object

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action):
        """-> (obs, reward, terminated, truncated, info)"""
        raise NotImplementedError


class VectorEnv:
    """Batch-of-envs with auto-reset on episode end.

    Built-in envs implement batched dynamics natively (`_step_batch`);
    arbitrary single envs are wrapped with a Python loop fallback.
    """

    def __init__(self, creator: Callable[..., Env], num_envs: int,
                 seed: int = 0):
        probe = creator()
        self.observation_space = probe.observation_space
        self.action_space = probe.action_space
        self.num_envs = num_envs
        self._batched = None
        self._envs = None
        if isinstance(probe, _BatchedEnv):
            try:
                # rebuild through the creator so constructor kwargs /
                # env_config survive (only the batch width changes)
                self._batched = creator(batch=num_envs)
            except TypeError:
                pass  # creator doesn't forward batch: loop fallback
        if self._batched is None:
            self._envs = [probe] + [creator() for _ in range(num_envs - 1)]
        self._rng = np.random.default_rng(seed)
        self._ep_ret = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self.episode_returns: list = []  # completed-episode returns
        self.episode_lengths: list = []

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        if self._batched is not None:
            return self._batched.reset_batch(self._rng)
        return np.stack([
            e.reset(seed=int(self._rng.integers(2**31)))
            for e in self._envs
        ])

    def step(self, actions: np.ndarray):
        """-> (obs, rewards, dones); finished sub-envs auto-reset, their
        returns recorded in episode_returns."""
        if self._batched is not None:
            obs, rew, term, trunc = self._batched.step_batch(
                actions, self._rng)
        else:
            obs_l, rew_l, term_l, trunc_l = [], [], [], []
            for e, a in zip(self._envs, actions):
                o, r, t, tr, _ = e.step(a)
                obs_l.append(o); rew_l.append(r)
                term_l.append(t); trunc_l.append(tr)
            obs = np.stack(obs_l)
            rew = np.asarray(rew_l, np.float32)
            term = np.asarray(term_l)
            trunc = np.asarray(trunc_l)
        done = term | trunc
        self._ep_ret += rew
        self._ep_len += 1
        if done.any():
            for i in np.flatnonzero(done):
                self.episode_returns.append(float(self._ep_ret[i]))
                self.episode_lengths.append(int(self._ep_len[i]))
            self._ep_ret[done] = 0.0
            self._ep_len[done] = 0
            if self._batched is not None:
                obs = self._batched.reset_where(obs, done, self._rng)
            else:
                for i in np.flatnonzero(done):
                    obs[i] = self._envs[i].reset(
                        seed=int(self._rng.integers(2**31)))
        return obs, rew, done

    def pop_episode_stats(self):
        rets, lens = self.episode_returns, self.episode_lengths
        self.episode_returns, self.episode_lengths = [], []
        return rets, lens


class _BatchedEnv(Env):
    """Envs whose dynamics vectorize over a batch axis natively."""

    def __init__(self, batch: int = 1):
        self.batch = batch

    def reset_batch(self, rng) -> np.ndarray:
        raise NotImplementedError

    def step_batch(self, actions, rng):
        raise NotImplementedError

    def reset_where(self, obs, done, rng) -> np.ndarray:
        raise NotImplementedError

    # single-env API falls out of the batched one
    def reset(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        return self.reset_batch(rng)[0]

    def step(self, action):
        obs, rew, term, trunc = self.step_batch(
            np.asarray([action]), np.random.default_rng(0))
        return obs[0], float(rew[0]), bool(term[0]), bool(trunc[0]), {}


class CartPole(_BatchedEnv):
    """Classic cart-pole balance, standard gymnasium-v1 constants
    (max 500 steps, reward 1/step)."""

    GRAVITY, MASSCART, MASSPOLE = 9.8, 1.0, 0.1
    LENGTH, FORCE_MAG, TAU = 0.5, 10.0, 0.02
    THETA_LIMIT, X_LIMIT, MAX_STEPS = 12 * np.pi / 180, 2.4, 500

    observation_space = Box(-np.inf, np.inf, (4,))
    action_space = Discrete(2)

    def __init__(self, batch: int = 1):
        super().__init__(batch)
        self._state = np.zeros((batch, 4), np.float64)
        self._t = np.zeros(batch, np.int64)

    def reset_batch(self, rng) -> np.ndarray:
        self._state = rng.uniform(-0.05, 0.05, (self.batch, 4))
        self._t[:] = 0
        return self._state.astype(np.float32)

    def step_batch(self, actions, rng):
        x, x_dot, th, th_dot = self._state.T
        force = np.where(np.asarray(actions) == 1,
                         self.FORCE_MAG, -self.FORCE_MAG)
        costh, sinth = np.cos(th), np.sin(th)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * th_dot**2 * sinth) / total_mass
        th_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costh**2 / total_mass)
        )
        x_acc = temp - polemass_length * th_acc * costh / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        th = th + self.TAU * th_dot
        th_dot = th_dot + self.TAU * th_acc
        self._state = np.stack([x, x_dot, th, th_dot], axis=1)
        self._t += 1
        term = (np.abs(x) > self.X_LIMIT) | (np.abs(th) > self.THETA_LIMIT)
        trunc = self._t >= self.MAX_STEPS
        rew = np.ones(self.batch, np.float32)
        return self._state.astype(np.float32), rew, term, trunc

    def reset_where(self, obs, done, rng) -> np.ndarray:
        idx = np.flatnonzero(done)
        self._state[idx] = rng.uniform(-0.05, 0.05, (len(idx), 4))
        self._t[idx] = 0
        obs = obs.copy()
        obs[idx] = self._state[idx].astype(np.float32)
        return obs


class Pendulum(_BatchedEnv):
    """Torque-controlled pendulum swing-up (continuous actions)."""

    MAX_SPEED, MAX_TORQUE, DT, G, M, L = 8.0, 2.0, 0.05, 10.0, 1.0, 1.0
    MAX_STEPS = 200

    observation_space = Box(-np.inf, np.inf, (3,))
    action_space = Box(-2.0, 2.0, (1,))

    def __init__(self, batch: int = 1):
        super().__init__(batch)
        self._th = np.zeros(batch)
        self._thdot = np.zeros(batch)
        self._t = np.zeros(batch, np.int64)

    def _obs(self):
        return np.stack(
            [np.cos(self._th), np.sin(self._th), self._thdot], axis=1
        ).astype(np.float32)

    def reset_batch(self, rng) -> np.ndarray:
        self._th = rng.uniform(-np.pi, np.pi, self.batch)
        self._thdot = rng.uniform(-1.0, 1.0, self.batch)
        self._t[:] = 0
        return self._obs()

    def step_batch(self, actions, rng):
        u = np.clip(np.asarray(actions).reshape(self.batch),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3 * self.G / (2 * self.L) * np.sin(th)
            + 3.0 / (self.M * self.L**2) * u
        ) * self.DT
        thdot = np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._th = th + thdot * self.DT
        self._thdot = thdot
        self._t += 1
        trunc = self._t >= self.MAX_STEPS
        term = np.zeros(self.batch, bool)
        return self._obs(), (-cost).astype(np.float32), term, trunc

    def reset_where(self, obs, done, rng) -> np.ndarray:
        idx = np.flatnonzero(done)
        self._th[idx] = rng.uniform(-np.pi, np.pi, len(idx))
        self._thdot[idx] = rng.uniform(-1.0, 1.0, len(idx))
        self._t[idx] = 0
        obs = obs.copy()
        obs[idx] = self._obs()[idx]
        return obs


register_env("CartPole-v1", CartPole)
register_env("Pendulum-v1", Pendulum)
