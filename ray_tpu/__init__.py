"""ray_tpu — a TPU-native distributed computing framework.

A ground-up re-design of the reference system (Ray) for TPU clusters:
tasks, actors, objects, and placement groups over a gRPC-style control
plane and shared-memory object store; jax/XLA/pjit as the in-slice data
plane; Pallas kernels for long-context attention; Train/Data/Serve/Tune
libraries built purely on the public core API.
"""
from ._private.core_worker import (  # noqa: F401
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    ObjectRefGenerator,
    RayActorError,
    RayError,
    RayTaskError,
    TaskCancelledError,
)
from .actor import ActorClass, ActorHandle, method  # noqa: F401
from .api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from .remote_function import RemoteFunction  # noqa: F401
from .util.placement_group import (  # noqa: F401
    placement_group,
    placement_group_table,
    remove_placement_group,
)

__version__ = "0.1.0"
