"""AIR common layer: configs shared across Train/Tune/Serve/Data.

Reference: python/ray/air/ — RunConfig/ScalingConfig/FailureConfig/
CheckpointConfig schemas plus result/session plumbing shared by
Train + Tune (air/config.py). The canonical definitions live in
ray_tpu.train.api (where the reference's train v2 also re-homes them);
this package is the stable import point:

    from ray_tpu.air import RunConfig, ScalingConfig, FailureConfig
"""
from ..train.api import FailureConfig, RunConfig, ScalingConfig
from ..train.checkpoint import Checkpoint

__all__ = ["RunConfig", "ScalingConfig", "FailureConfig", "Checkpoint"]
