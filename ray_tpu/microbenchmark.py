"""Core runtime microbenchmarks (`python -m ray_tpu.microbenchmark`).

Mirrors the shape of the reference's `ray microbenchmark` harness
(reference: python/ray/_private/ray_perf.py:1, invoked from
scripts/scripts.py:2012) so the numbers line up row-for-row with the
published v2.9.3 release logs (BASELINE.md). Writes BENCH_core.json.

Timing protocol (compressed from ray_microbenchmark_helpers.timeit): short
warmup, then REPS timed windows of WINDOW_S seconds; reports mean ± sd
ops/sec.
"""
from __future__ import annotations

import json
import multiprocessing
import os
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

import ray_tpu as ray

WARMUP_S = float(os.environ.get("RAY_TPU_BENCH_WARMUP_S", "0.5"))
WINDOW_S = float(os.environ.get("RAY_TPU_BENCH_WINDOW_S", "1.5"))
REPS = int(os.environ.get("RAY_TPU_BENCH_REPS", "3"))
FILTER = os.environ.get("TESTS_TO_RUN", "")

# v2.9.3 reference values (ops/sec) from
# release/release_logs/2.9.3/microbenchmark.json (see BASELINE.md).
REFERENCE = {
    "single client get calls": 10182.0,
    "single client put calls": 5545.0,
    "single client put gigabytes": 20.88,
    "single client tasks sync": 1007.0,
    "single client tasks async": 8444.0,
    "multi client tasks async": 25166.0,
    "single client wait 1k refs": 5.49,
    "1:1 actor calls sync": 2033.0,
    "1:1 actor calls async": 8886.0,
    "1:1 actor calls concurrent": 5095.0,
    "1:1 async-actor calls async": 3434.0,
    "n:n actor calls async": 27667.0,
    "single client get object containing 10k refs": 12.39,
}


def timeit(name: str, fn: Callable[[], None], multiplier: float = 1,
           results: Optional[list] = None):
    if FILTER and FILTER not in name:
        return
    # warmup
    start = time.perf_counter()
    while time.perf_counter() - start < WARMUP_S:
        fn()
    stats = []
    for _ in range(REPS):
        count = 0
        start = time.perf_counter()
        while time.perf_counter() - start < WINDOW_S:
            fn()
            count += 1
        stats.append(multiplier * count / (time.perf_counter() - start))
    mean, sd = float(np.mean(stats)), float(np.std(stats))
    ref = REFERENCE.get(name)
    ratio = (mean / ref) if ref else None
    line = f"{name}: {mean:.2f} +- {sd:.2f} /s"
    if ref:
        line += f"  (ref {ref:.2f}, {ratio:.2f}x)"
    print(line, flush=True)
    if results is not None:
        results.append({
            "name": name, "ops_per_s": round(mean, 2), "sd": round(sd, 2),
            "reference": ref, "vs_reference": round(ratio, 3) if ratio else None,
        })


@ray.remote
def small_value():
    return b"ok"


@ray.remote
class Actor:
    def small_value(self):
        return b"ok"

    def small_value_arg(self, x):
        return b"ok"


@ray.remote
class AsyncActor:
    async def small_value(self):
        return b"ok"


@ray.remote
class Client:
    """Driver-side fan-out client (reference ray_perf.py Client)."""

    def __init__(self, servers):
        self.servers = servers

    def small_value_batch(self, n):
        refs = []
        for s in self.servers:
            refs.extend([s.small_value.remote() for _ in range(n)])
        ray.get(refs)


@ray.remote
def batch_submitter(n):
    ray.get([small_value.remote() for _ in range(n)])
    return 0


@ray.remote
def make_object_with_refs(n):
    return [ray.put(i) for i in range(n)]


def main() -> List[dict]:
    results: List[dict] = []
    # Explicit CPU slots: the benchmarks need concurrent workers even on a
    # small host (processes timeshare; the reference runs on 64-core
    # machines where the default suffices).
    ray.init(resources={"CPU": float(os.environ.get(
        "RAY_TPU_BENCH_CPUS", max(8, (os.cpu_count() or 1) * 2)))})
    try:
        value = ray.put(0)
        timeit("single client get calls", lambda: ray.get(value),
               results=results)
        timeit("single client put calls", lambda: ray.put(0),
               results=results)

        arr = np.zeros(64 * 1024 * 1024 // 8, dtype=np.int64)  # 64 MiB
        timeit("single client put gigabytes", lambda: ray.put(arr),
               multiplier=64 / 1024, results=results)

        timeit("single client tasks sync",
               lambda: ray.get(small_value.remote()), results=results)
        timeit("single client tasks async",
               lambda: ray.get([small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        n, m = 1000, 4
        timeit(
            "multi client tasks async",
            lambda: ray.get(
                [batch_submitter.remote(n) for _ in range(m)]
            ),
            multiplier=n * m,
            results=results,
        )

        def wait_1k():
            not_ready = [small_value.remote() for _ in range(1000)]
            fetch_local = True
            while not_ready:
                _r, not_ready = ray.wait(not_ready,
                                         fetch_local=fetch_local)
                fetch_local = False

        timeit("single client wait 1k refs", wait_1k, results=results)

        a = Actor.remote()
        timeit("1:1 actor calls sync",
               lambda: ray.get(a.small_value.remote()), results=results)
        timeit("1:1 actor calls async",
               lambda: ray.get([a.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        ac = Actor.options(max_concurrency=16).remote()
        timeit("1:1 actor calls concurrent",
               lambda: ray.get([ac.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        aa = AsyncActor.remote()
        timeit("1:1 async-actor calls async",
               lambda: ray.get([aa.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        # n:n — n_cpu submitter actors each driving one server actor
        n_cpu = max(2, min(8, multiprocessing.cpu_count() // 2))
        nn = 1000
        servers = [Actor.remote() for _ in range(n_cpu)]
        clients = [Client.remote([s]) for s in servers]
        timeit(
            "n:n actor calls async",
            lambda: ray.get(
                [c.small_value_batch.remote(nn) for c in clients]
            ),
            multiplier=nn * n_cpu,
            results=results,
        )

        refs_obj = make_object_with_refs.remote(10000)
        ray.get(refs_obj)  # materialize once
        timeit("single client get object containing 10k refs",
               lambda: ray.get(refs_obj), results=results)
    finally:
        ray.shutdown()
    return results


if __name__ == "__main__":
    out = main()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_core.json")
    # repo root may not be the parent (installed package): fall back to cwd
    if not os.path.isdir(os.path.dirname(path)):
        path = "BENCH_core.json"
    with open(path, "w") as f:
        json.dump(
            {
                "benchmarks": out,
                "window_s": WINDOW_S,
                "reps": REPS,
                # the reference numbers were measured on 64-core m5zn
                # hosts (release/release_logs/2.9.3); throughput rows
                # that fan out across processes are CPU-bound on small
                # hosts, so record the environment for comparability
                "host_cpus": os.cpu_count(),
            },
            f, indent=2)
    print(f"wrote {path}")
