"""Core runtime microbenchmarks (`python -m ray_tpu.microbenchmark`).

Mirrors the shape of the reference's `ray microbenchmark` harness
(reference: python/ray/_private/ray_perf.py:1, invoked from
scripts/scripts.py:2012) so the numbers line up row-for-row with the
published v2.9.3 release logs (BASELINE.md). Writes BENCH_core.json.

Timing protocol (compressed from ray_microbenchmark_helpers.timeit): short
warmup, then REPS timed windows of WINDOW_S seconds; reports mean ± sd
ops/sec.
"""
from __future__ import annotations

import json
import multiprocessing
import os
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

import ray_tpu as ray

WARMUP_S = float(os.environ.get("RAY_TPU_BENCH_WARMUP_S", "0.5"))
WINDOW_S = float(os.environ.get("RAY_TPU_BENCH_WINDOW_S", "1.5"))
REPS = int(os.environ.get("RAY_TPU_BENCH_REPS", "3"))
FILTER = os.environ.get("TESTS_TO_RUN", "")

# v2.9.3 reference values (ops/sec) from
# release/release_logs/2.9.3/microbenchmark.json (see BASELINE.md).
REFERENCE = {
    "single client get calls": 10182.0,
    "single client put calls": 5545.0,
    "single client put gigabytes": 20.88,
    "multi client put calls": 12677.0,
    "multi client put gigabytes": 35.88,
    "single client tasks sync": 1007.0,
    "single client tasks async": 8444.0,
    "single client tasks and get batch": 8.48,
    "multi client tasks async": 25166.0,
    "single client wait 1k refs": 5.49,
    "1:1 actor calls sync": 2033.0,
    "1:1 actor calls async": 8886.0,
    "1:1 actor calls concurrent": 5095.0,
    "1:1 async-actor calls async": 3434.0,
    "1:1 async-actor calls sync": 1291.6,
    "1:1 async-actor calls with args async": 2307.2,
    "1:n actor calls async": 8570.0,
    "1:n async-actor calls async": 7455.8,
    "n:n actor calls async": 27667.0,
    "n:n actor calls with arg async": 2829.3,
    "n:n async-actor calls async": 22927.1,
    "single client get object containing 10k refs": 12.39,
    "client: get calls": 1151.5,
    "client: put calls": 824.8,
    "client: tasks and put batch": 10856.4,
    "client: 1:1 actor calls async": 1016.9,
}


def timeit(name: str, fn: Callable[[], None], multiplier: float = 1,
           results: Optional[list] = None):
    if FILTER and FILTER not in name:
        return
    # warmup
    start = time.perf_counter()
    while time.perf_counter() - start < WARMUP_S:
        fn()
    stats = []
    for _ in range(REPS):
        count = 0
        start = time.perf_counter()
        while time.perf_counter() - start < WINDOW_S:
            fn()
            count += 1
        stats.append(multiplier * count / (time.perf_counter() - start))
    mean, sd = float(np.mean(stats)), float(np.std(stats))
    ref = REFERENCE.get(name)
    ratio = (mean / ref) if ref else None
    line = f"{name}: {mean:.2f} +- {sd:.2f} /s"
    if ref:
        line += f"  (ref {ref:.2f}, {ratio:.2f}x)"
    print(line, flush=True)
    if results is not None:
        results.append({
            "name": name, "ops_per_s": round(mean, 2), "sd": round(sd, 2),
            "reference": ref, "vs_reference": round(ratio, 3) if ratio else None,
        })


@ray.remote
def small_value():
    return b"ok"


@ray.remote(num_cpus=0)
class Actor:
    def small_value(self):
        return b"ok"

    def small_value_arg(self, x):
        return b"ok"


@ray.remote(num_cpus=0)
class AsyncActor:
    async def small_value(self):
        return b"ok"

    async def small_value_arg(self, x):
        return b"ok"


@ray.remote(num_cpus=0)
class Client:
    """Driver-side fan-out client (reference ray_perf.py Client)."""

    def __init__(self, servers):
        self.servers = servers

    def small_value_batch(self, n):
        refs = []
        for s in self.servers:
            refs.extend([s.small_value.remote() for _ in range(n)])
        ray.get(refs)

    def small_value_batch_arg(self, n):
        v = ray.put(0)
        refs = []
        for s in self.servers:
            refs.extend([s.small_value_arg.remote(v) for _ in range(n)])
        ray.get(refs)


@ray.remote(num_cpus=0)
class PutClient:
    """Multi-client object-store driver (reference: multi-proc put rows)."""

    def put_small_batch(self, n):
        for _ in range(n):
            ray.put(0)
        return 0

    def put_gigabytes_batch(self, n, mib):
        arr = np.zeros(mib * 1024 * 1024 // 8, dtype=np.int64)
        for _ in range(n):
            ray.put(arr)
        return 0


@ray.remote
def batch_submitter(n):
    ray.get([small_value.remote() for _ in range(n)])
    return 0


@ray.remote
def make_object_with_refs(n):
    return [ray.put(i) for i in range(n)]


def bench_init():
    """Shared harness init for the microbenchmark + scalability envelope.

    CPU slots govern concurrent WORKER processes; benchmark fixture
    actors declare num_cpus=0 so they never eat the pool (the reference
    harness ran on 64-core machines where this couldn't matter).
    host_cpus is recorded in each JSON so ratios stay honest."""
    ray.init(resources={"CPU": float(os.environ.get(
        "RAY_TPU_BENCH_CPUS", max(8, 2 * (os.cpu_count() or 1))))})


def _host_memcpy_gib_s() -> float:
    """Raw single-thread memcpy bandwidth: the hardware ceiling for
    put/get GiB/s rows (the reference's machines had several times this
    host's memory bandwidth — ratios need the denominator recorded)."""
    a = np.ones(32 * 1024 * 1024 // 8, dtype=np.int64)
    b = np.empty_like(a)
    b[:] = a  # warm
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 0.5:
        b[:] = a
        n += 1
    return round(n * 32 / 1024 / (time.perf_counter() - t0), 2)


def write_bench_json(filename: str, payload: dict):
    """Write a benchmark JSON next to the repo root (fallback: cwd)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), filename)
    if not os.path.isdir(os.path.dirname(path)):
        path = filename
    payload = dict(payload, host_cpus=os.cpu_count(),
                   host_memcpy_gib_s=_host_memcpy_gib_s())
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    print(f"wrote {path}")


def main() -> List[dict]:
    results: List[dict] = []
    bench_init()
    try:
        value = ray.put(0)
        timeit("single client get calls", lambda: ray.get(value),
               results=results)
        timeit("single client put calls", lambda: ray.put(0),
               results=results)

        arr = np.zeros(64 * 1024 * 1024 // 8, dtype=np.int64)  # 64 MiB
        timeit("single client put gigabytes", lambda: ray.put(arr),
               multiplier=64 / 1024, results=results)

        n_put = max(2, min(4, multiprocessing.cpu_count()))
        putters = [PutClient.remote() for _ in range(n_put)]
        timeit(
            "multi client put calls",
            lambda: ray.get(
                [p.put_small_batch.remote(100) for p in putters]
            ),
            multiplier=100 * n_put,
            results=results,
        )
        timeit(
            "multi client put gigabytes",
            lambda: ray.get(
                [p.put_gigabytes_batch.remote(2, 64) for p in putters]
            ),
            multiplier=2 * n_put * 64 / 1024,
            results=results,
        )

        timeit("single client tasks sync",
               lambda: ray.get(small_value.remote()), results=results)
        timeit("single client tasks async",
               lambda: ray.get([small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)
        timeit("single client tasks and get batch",
               lambda: ray.get([small_value.remote() for _ in range(1000)]),
               results=results)

        n, m = 1000, 4
        timeit(
            "multi client tasks async",
            lambda: ray.get(
                [batch_submitter.remote(n) for _ in range(m)]
            ),
            multiplier=n * m,
            results=results,
        )

        def wait_1k():
            not_ready = [small_value.remote() for _ in range(1000)]
            fetch_local = True
            while not_ready:
                _r, not_ready = ray.wait(not_ready,
                                         fetch_local=fetch_local)
                fetch_local = False

        timeit("single client wait 1k refs", wait_1k, results=results)

        a = Actor.remote()
        timeit("1:1 actor calls sync",
               lambda: ray.get(a.small_value.remote()), results=results)
        timeit("1:1 actor calls async",
               lambda: ray.get([a.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        ac = Actor.options(max_concurrency=16).remote()
        timeit("1:1 actor calls concurrent",
               lambda: ray.get([ac.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)

        aa = AsyncActor.remote()
        timeit("1:1 async-actor calls async",
               lambda: ray.get([aa.small_value.remote() for _ in range(1000)]),
               multiplier=1000, results=results)
        timeit("1:1 async-actor calls sync",
               lambda: ray.get(aa.small_value.remote()), results=results)
        v_arg = ray.put(0)
        timeit("1:1 async-actor calls with args async",
               lambda: ray.get(
                   [aa.small_value_arg.remote(v_arg) for _ in range(1000)]),
               multiplier=1000, results=results)

        # 1:n — one driver fanning out over n server actors
        n_cpu = max(2, min(8, multiprocessing.cpu_count() // 2))
        fan_servers = [Actor.remote() for _ in range(n_cpu)]
        per = max(1, 1000 // n_cpu)
        timeit(
            "1:n actor calls async",
            lambda: ray.get([s.small_value.remote()
                             for s in fan_servers for _ in range(per)]),
            multiplier=per * n_cpu,
            results=results,
        )
        fan_async = [AsyncActor.remote() for _ in range(n_cpu)]
        timeit(
            "1:n async-actor calls async",
            lambda: ray.get([s.small_value.remote()
                             for s in fan_async for _ in range(per)]),
            multiplier=per * n_cpu,
            results=results,
        )

        # n:n — n_cpu submitter actors each driving one server actor
        nn = 1000
        servers = [Actor.remote() for _ in range(n_cpu)]
        clients = [Client.remote([s]) for s in servers]
        timeit(
            "n:n actor calls async",
            lambda: ray.get(
                [c.small_value_batch.remote(nn) for c in clients]
            ),
            multiplier=nn * n_cpu,
            results=results,
        )
        timeit(
            "n:n actor calls with arg async",
            lambda: ray.get(
                [c.small_value_batch_arg.remote(nn) for c in clients]
            ),
            multiplier=nn * n_cpu,
            results=results,
        )
        aservers = [AsyncActor.remote() for _ in range(n_cpu)]
        aclients = [Client.remote([s]) for s in aservers]
        timeit(
            "n:n async-actor calls async",
            lambda: ray.get(
                [c.small_value_batch.remote(nn) for c in aclients]
            ),
            multiplier=nn * n_cpu,
            results=results,
        )

        refs_obj = make_object_with_refs.remote(10000)
        ray.get(refs_obj)  # materialize once
        timeit("single client get object containing 10k refs",
               lambda: ray.get(refs_obj), results=results)

        _client_rows(results)
    finally:
        ray.shutdown()
    return results


def _client_rows(results: List[dict]):
    """Ray Client (`ray://`) rows: a remote driver over one socket
    (reference ray_perf.py 'client: ...' rows run the same ops through
    the client server)."""
    from ray_tpu.util.client import ClientServer, ClientWorker

    srv = ClientServer(port=0)
    try:
        w = ClientWorker(*srv.address)
        try:
            v = w.put(0)
            timeit("client: get calls", lambda: w.get(v), results=results)
            timeit("client: put calls", lambda: w.put(0), results=results)

            cf = w.remote(lambda: b"ok")
            w.get([cf.remote() for _ in range(10)])  # warm + export
            timeit(
                "client: tasks and put batch",
                lambda: w.get([cf.remote() for _ in range(100)]),
                multiplier=100,
                results=results,
            )

            class _A:
                def small_value(self):
                    return b"ok"

            ca = w.remote(_A).remote()
            w.get(ca.small_value.remote())
            timeit(
                "client: 1:1 actor calls async",
                lambda: w.get(
                    [ca.small_value.remote() for _ in range(100)]),
                multiplier=100,
                results=results,
            )
        finally:
            w.disconnect()
    finally:
        srv.stop()


if __name__ == "__main__":
    out = main()
    if FILTER:
        # a filtered debug run must never clobber the committed
        # full-table artifact
        print(f"TESTS_TO_RUN={FILTER!r}: skipping BENCH_core.json write")
    else:
        # the reference numbers were measured on 64-core m5zn hosts
        # (release/release_logs/2.9.3); throughput rows that fan out
        # across processes are CPU-bound on small hosts, so
        # write_bench_json records host_cpus for comparability
        write_bench_json("BENCH_core.json", {
            "benchmarks": out, "window_s": WINDOW_S, "reps": REPS,
        })
