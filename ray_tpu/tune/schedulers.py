"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/trial_scheduler.py (decision
constants), async_hyperband.py (ASHAScheduler / _Bracket.on_result),
median_stopping_rule.py, pbt.py (PopulationBasedTraining exploit/explore).
Redesigned around a single on_result() hook returning a decision; the
controller owns actor lifecycle.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
# Trial consumed its full budget (max_t) — normal termination, not an
# early stop (reference: Trainable stops itself at stopping criteria).
COMPLETE = "COMPLETE"
# (EXPLOIT, source_trial) — restart this trial from source's checkpoint
# with a perturbed config (PBT only).
EXPLOIT = "EXPLOIT"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def on_trial_add(self, trial: Trial):
        pass

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  trials: List[Trial]):
        """Return CONTINUE / STOP / (EXPLOIT, source_trial)."""
        return CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Rung:
    """One ASHA rung: milestone iteration + recorded metrics."""

    def __init__(self, milestone: int):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}

    def cutoff(self, rf: float) -> Optional[float]:
        if len(self.recorded) < rf:
            return None
        vals = np.asarray(list(self.recorded.values()))
        # keep the top 1/rf fraction → cutoff at the (1-1/rf) quantile
        return float(np.quantile(vals, 1.0 - 1.0 / rf))


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving (reference:
    tune/schedulers/async_hyperband.py AsyncHyperBandScheduler with
    brackets=1, the recommended default)."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[_Rung] = []
        m = grace_period
        while m < max_t:
            self.rungs.append(_Rung(m))
            m = int(np.ceil(m * reduction_factor))
        self.rungs.reverse()  # highest milestone first (match reference)

    def on_result(self, trial, result, trials):
        t = result.get(self.time_attr, trial.iteration)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = value if self.mode == "max" else -value
        if t >= self.max_t:
            return COMPLETE  # trial consumed its budget
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            cutoff = rung.cutoff(self.rf)
            rung.recorded[trial.trial_id] = score
            if cutoff is not None and score < cutoff:
                decision = STOP
            break  # only the highest applicable rung (async halving)
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running means at the same iteration (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = {}

    def on_result(self, trial, result, trials):
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = value if self.mode == "max" else -value
        self._history.setdefault(trial.trial_id, []).append(score)
        t = result.get(self.time_attr, trial.iteration)
        if t < self.grace_period:
            return CONTINUE
        means = [
            float(np.mean(h))
            for tid, h in self._history.items()
            if tid != trial.trial_id
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        best = max(self._history[trial.trial_id])
        if best < float(np.median(means)):
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone the
    checkpoint of a top-quantile trial and perturb its hyperparameters."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: str = "max",
        perturbation_interval: int = 4,
        quantile_fraction: float = 0.25,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.rng = np.random.default_rng(seed)
        self.time_attr = time_attr

    def _score(self, trial: Trial) -> Optional[float]:
        v = trial.metric(self.metric)
        if v is None:
            return None
        return v if self.mode == "max" else -v

    def on_result(self, trial, result, trials):
        t = result.get(self.time_attr, trial.iteration)
        if t - trial.last_perturb_iter < self.interval:
            return CONTINUE
        trial.last_perturb_iter = t
        scored: List[Tuple[float, Trial]] = []
        for other in trials:
            s = self._score(other)
            if s is not None:
                scored.append((s, other))
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda p: p[0])
        k = max(1, int(len(scored) * self.quantile))
        bottom = [p[1] for p in scored[:k]]
        top = [p[1] for p in scored[-k:]]
        if any(o.trial_id == trial.trial_id for o in bottom):
            candidates = [
                o for o in top
                if o.trial_id != trial.trial_id and o.checkpoint_path
            ]
            if candidates:
                source = candidates[
                    int(self.rng.integers(len(candidates)))
                ]
                return (EXPLOIT, source)
        return CONTINUE
