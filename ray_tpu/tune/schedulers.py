"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/trial_scheduler.py (decision
constants), async_hyperband.py (ASHAScheduler / _Bracket.on_result),
median_stopping_rule.py, pbt.py (PopulationBasedTraining exploit/explore).
Redesigned around a single on_result() hook returning a decision; the
controller owns actor lifecycle.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
# Trial consumed its full budget (max_t) — normal termination, not an
# early stop (reference: Trainable stops itself at stopping criteria).
COMPLETE = "COMPLETE"
# (EXPLOIT, source_trial) — restart this trial from source's checkpoint
# with a perturbed config (PBT only).
EXPLOIT = "EXPLOIT"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def on_trial_add(self, trial: Trial):
        pass

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  trials: List[Trial]):
        """Return CONTINUE / STOP / (EXPLOIT, source_trial)."""
        return CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Rung:
    """One ASHA rung: milestone iteration + recorded metrics."""

    def __init__(self, milestone: int):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}

    def cutoff(self, rf: float) -> Optional[float]:
        if len(self.recorded) < rf:
            return None
        vals = np.asarray(list(self.recorded.values()))
        # keep the top 1/rf fraction → cutoff at the (1-1/rf) quantile
        return float(np.quantile(vals, 1.0 - 1.0 / rf))


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving (reference:
    tune/schedulers/async_hyperband.py AsyncHyperBandScheduler with
    brackets=1, the recommended default)."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[_Rung] = []
        m = grace_period
        while m < max_t:
            self.rungs.append(_Rung(m))
            m = int(np.ceil(m * reduction_factor))
        self.rungs.reverse()  # highest milestone first (match reference)

    def on_result(self, trial, result, trials):
        t = result.get(self.time_attr, trial.iteration)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = value if self.mode == "max" else -value
        if t >= self.max_t:
            return COMPLETE  # trial consumed its budget
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            cutoff = rung.cutoff(self.rf)
            rung.recorded[trial.trial_id] = score
            if cutoff is not None and score < cutoff:
                decision = STOP
            break  # only the highest applicable rung (async halving)
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running means at the same iteration (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = {}

    def on_result(self, trial, result, trials):
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = value if self.mode == "max" else -value
        self._history.setdefault(trial.trial_id, []).append(score)
        t = result.get(self.time_attr, trial.iteration)
        if t < self.grace_period:
            return CONTINUE
        means = [
            float(np.mean(h))
            for tid, h in self._history.items()
            if tid != trial.trial_id
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        best = max(self._history[trial.trial_id])
        if best < float(np.median(means)):
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone the
    checkpoint of a top-quantile trial and perturb its hyperparameters."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: str = "max",
        perturbation_interval: int = 4,
        quantile_fraction: float = 0.25,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.rng = np.random.default_rng(seed)
        self.time_attr = time_attr

    def _score(self, trial: Trial) -> Optional[float]:
        v = trial.metric(self.metric)
        if v is None:
            return None
        return v if self.mode == "max" else -v

    def on_result(self, trial, result, trials):
        t = result.get(self.time_attr, trial.iteration)
        if t - trial.last_perturb_iter < self.interval:
            return CONTINUE
        trial.last_perturb_iter = t
        scored: List[Tuple[float, Trial]] = []
        for other in trials:
            s = self._score(other)
            if s is not None:
                scored.append((s, other))
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda p: p[0])
        k = max(1, int(len(scored) * self.quantile))
        bottom = [p[1] for p in scored[:k]]
        top = [p[1] for p in scored[-k:]]
        if any(o.trial_id == trial.trial_id for o in bottom):
            candidates = [
                o for o in top
                if o.trial_id != trial.trial_id and o.checkpoint_path
            ]
            if candidates:
                source = candidates[
                    int(self.rng.integers(len(candidates)))
                ]
                return (EXPLOIT, source)
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Classic (bracketed) HyperBand (reference:
    tune/schedulers/hyperband.py HyperBandScheduler): trials are dealt
    round-robin into s_max+1 brackets; bracket s starts its trials with
    budget max_t * eta^-s and successively halves at each rung, keeping
    the top 1/eta. Unlike ASHA (one bracket, async), the bracket
    structure hedges between "many short trials" and "few long trials"."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.s_max = int(np.floor(np.log(max_t) / np.log(self.eta)))
        # bracket s: rungs at max_t * eta^(i - s) for i in 0..s
        self._brackets: List[List[_Rung]] = []
        for s in range(self.s_max + 1):
            rungs = [
                _Rung(int(np.ceil(max_t * self.eta ** (i - s))))
                for i in range(s)
            ]
            rungs.sort(key=lambda r: -r.milestone)
            self._brackets.append(rungs)
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def on_trial_add(self, trial):
        # deal round-robin over brackets (reference fills brackets by
        # capacity; round-robin keeps every bracket live at small n)
        self._assignment[trial.trial_id] = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % len(
            self._brackets)

    def on_result(self, trial, result, trials):
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        t = result.get(self.time_attr, trial.iteration)
        if t >= self.max_t:
            return COMPLETE
        score = value if self.mode == "max" else -value
        rungs = self._brackets[self._assignment.get(trial.trial_id, 0)]
        for rung in rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            cutoff = rung.cutoff(self.eta)
            rung.recorded[trial.trial_id] = score
            if cutoff is not None and score < cutoff:
                return STOP
            break
        return CONTINUE


class PB2(PopulationBasedTraining):
    """PBT with a model-guided explore step (reference:
    tune/schedulers/pb2.py — GP-bandit selection of the next
    hyperparameters instead of random perturbation). The exploit
    decision is inherited; explore() fits a tiny RBF-kernel GP on
    (hyperparam vector → recent reward delta) across the population and
    picks the UCB-best of K candidate perturbations — no sklearn/GPy
    dependency."""

    def __init__(self, *args, ucb_kappa: float = 1.0,
                 n_candidates: int = 16, **kwargs):
        super().__init__(*args, **kwargs)
        self.ucb_kappa = ucb_kappa
        self.n_candidates = n_candidates
        # (config-vector, delta) observations, bounded
        self._deltas: List[Tuple[np.ndarray, float]] = []
        self._last_score: Dict[str, float] = {}

    # -- observation capture ------------------------------------------
    def on_result(self, trial, result, trials):
        v = result.get(self.metric)
        if v is not None:
            s = v if self.mode == "max" else -v
            prev = self._last_score.get(trial.trial_id)
            if prev is not None:
                vec = self._vectorize(trial.config)
                if vec is not None:
                    self._deltas.append((vec, s - prev))
                    if len(self._deltas) > 256:
                        self._deltas.pop(0)
            self._last_score[trial.trial_id] = s
        decision = super().on_result(trial, result, trials)
        if isinstance(decision, tuple) and decision[0] == EXPLOIT:
            # the trial restarts from ANOTHER trial's checkpoint: its
            # next score jump is the clone, not the new hyperparams —
            # never feed that delta to the GP
            self._last_score.pop(trial.trial_id, None)
        return decision

    # -- model-guided explore (called by the tuner on EXPLOIT) --------
    def explore(self, source_config, param_space, rng):
        from . import search as search_mod

        candidates = [
            search_mod.perturb_config(source_config, param_space, rng)
            for _ in range(self.n_candidates)
        ]
        # vectors can be ragged (mixed-type choices vectorize to
        # different lengths): model only the modal length, and fall
        # back to the first candidate on any numerical failure — a
        # surrogate hiccup must never kill the experiment
        try:
            return self._explore_gp(candidates)
        except Exception:  # noqa: BLE001 — surrogate must never kill fit()
            return candidates[0]

    def _explore_gp(self, candidates):
        cand_vecs = [self._vectorize(c) for c in candidates]
        dim = next((len(v) for v in cand_vecs if v is not None), 0)
        obs = [(v, d) for v, d in self._deltas if len(v) == dim]
        if dim == 0 or len(obs) < 4:
            return candidates[0]
        X = np.stack([v for v, _d in obs])
        y = np.asarray([d for _v, d in obs])
        y = (y - y.mean()) / (y.std() + 1e-8)
        keep = [i for i, v in enumerate(cand_vecs)
                if v is not None and len(v) == dim]
        if not keep:
            return candidates[0]
        Xc = np.stack([cand_vecs[i] for i in keep])
        candidates = [candidates[i] for i in keep]
        # normalize per dimension for a unit-lengthscale RBF kernel
        mu, sd = X.mean(0), X.std(0) + 1e-8
        Xn, Xcn = (X - mu) / sd, (Xc - mu) / sd

        def rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2)

        K = rbf(Xn, Xn) + 1e-3 * np.eye(len(Xn))
        Ks = rbf(Xcn, Xn)
        Kinv = np.linalg.inv(K)
        mean = Ks @ Kinv @ y
        var = np.clip(1.0 - np.einsum("ij,jk,ik->i", Ks, Kinv, Ks),
                      1e-9, None)
        ucb = mean + self.ucb_kappa * np.sqrt(var)
        return candidates[int(np.argmax(ucb))]

    def _vectorize(self, config) -> Optional[np.ndarray]:
        vals = []
        for v in _flatten(config):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            vals.append(float(v))
        return np.asarray(vals) if vals else None


def _flatten(cfg):
    for v in cfg.values():
        if isinstance(v, dict):
            yield from _flatten(v)
        else:
            yield v
