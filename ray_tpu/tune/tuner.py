"""Tuner + trial controller.

Reference call stack: Tuner.fit (python/ray/tune/tuner.py:312) →
TuneController event loop (tune/execution/tune_controller.py:68) driving
one actor per trial, feeding results to a TrialScheduler, checkpointing
experiment state for Tuner.restore.

TPU-native shape: the controller is a driver-side loop (fit() blocks);
each trial is one actor whose trainable runs on a thread and reports
through a polled mailbox — the same gang pattern as train/api.py. Trials
are the unit of placement: resources per trial map to actor resources, so
a TPU trial occupies a whole host slice and the cluster caps concurrency.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..train.api import RunConfig
from ..train.checkpoint import Checkpoint
from . import schedulers as sched_mod
from . import search as search_mod
from .schedulers import (
    COMPLETE,
    CONTINUE,
    EXPLOIT,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from .trial import ERROR, PENDING, RUNNING, TERMINATED, Trial

# ---------------------------------------------------------------------------
# trainable-side session (reference: ray.tune.report / get_checkpoint)
# ---------------------------------------------------------------------------


class _Session:
    def __init__(self, trial_id: str, config: Dict[str, Any],
                 checkpoint: Optional[Checkpoint], workdir: str):
        self.trial_id = trial_id
        self.config = config
        self.checkpoint = checkpoint
        self.workdir = workdir
        self.iteration = 0
        self.reports: List[dict] = []
        self.lock = threading.Lock()


_session: Optional[_Session] = None


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from a trainable."""
    s = _session
    if s is None:
        raise RuntimeError("tune.report() called outside a trial")
    with s.lock:
        s.iteration += 1
        m = dict(metrics)
        m.setdefault("training_iteration", s.iteration)
        s.reports.append(
            {
                "metrics": m,
                "checkpoint_path": checkpoint.path if checkpoint else None,
            }
        )


def get_checkpoint() -> Optional[Checkpoint]:
    s = _session
    return s.checkpoint if s else None


def get_trial_id() -> str:
    s = _session
    return s.trial_id if s else ""


def get_trial_dir() -> str:
    s = _session
    return s.workdir if s else ""


class _TrialActor:
    """Runs one trial's trainable on a thread; controller polls."""

    def __init__(self, trial_id: str, workdir: str):
        self.trial_id = trial_id
        self.workdir = workdir
        self._done = False
        self._error: Optional[str] = None

    def run(self, payload: bytes, config: Dict[str, Any],
            checkpoint_path: Optional[str],
            start_iteration: int = 0) -> bool:
        import cloudpickle

        trainable = cloudpickle.loads(payload)
        global _session
        _session = _Session(
            self.trial_id, config,
            Checkpoint(checkpoint_path) if checkpoint_path else None,
            self.workdir,
        )
        # training_iteration counts cumulatively across restarts
        # (reference: Trainable keeps _iteration in restored state).
        _session.iteration = start_iteration
        self._s = _session

        def target():
            try:
                trainable(config)
            except Exception:
                self._error = traceback.format_exc()
            finally:
                self._done = True

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        with self._s.lock:
            reports, self._s.reports = self._s.reports, []
        return {"done": self._done, "error": self._error,
                "reports": reports}


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------


@dataclass
class TuneConfig:
    """Reference: ray.tune.TuneConfig."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    # model-based sequential searcher (search.Searcher, e.g.
    # TPESearcher); None = BasicVariant up-front generation
    search_alg: Optional[Any] = None
    seed: Optional[int] = None
    max_failures_per_trial: int = 0
    trial_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1}
    )


def with_resources(trainable: Callable, resources: Dict[str, float]):
    """Reference: tune.with_resources."""
    trainable._tune_resources = dict(resources)  # type: ignore
    return trainable


class TuneError(Exception):
    pass


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    path: str

    @property
    def metrics_dataframe(self):  # pragma: no cover - convenience
        import pandas as pd

        hist_file = os.path.join(self.path, "result.jsonl")
        rows = []
        if os.path.exists(hist_file):
            with open(hist_file) as f:
                rows = [json.loads(line) for line in f]
        return pd.DataFrame(rows)


class ResultGrid:
    def __init__(self, results: List[TrialResult], experiment_path: str):
        self._results = results
        self.experiment_path = experiment_path

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TrialResult:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or getattr(self, "_metric", None)
        mode = mode or getattr(self, "_mode", "max")
        scored = [r for r in self._results
                  if r.metrics.get(metric) is not None]
        if not scored:
            raise TuneError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored,
                                                              key=key)


# ---------------------------------------------------------------------------
# Tuner / controller
# ---------------------------------------------------------------------------


class Tuner:
    """Reference: ray.tune.Tuner (tuner.py:43). fit() runs the trial
    event loop; restore() resumes an interrupted experiment."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials: Optional[List[Trial]] = None

    # -- experiment persistence ---------------------------------------
    @classmethod
    def restore(cls, experiment_path: str, trainable: Callable) -> "Tuner":
        state_file = os.path.join(experiment_path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        # param_space (may contain Domain objects) rides a pickle sidecar
        param_space = {}
        ps_file = os.path.join(experiment_path, "param_space.pkl")
        if os.path.exists(ps_file):
            import cloudpickle

            with open(ps_file, "rb") as f:
                param_space = cloudpickle.load(f)
        tuner = cls(
            trainable,
            param_space=param_space,
            tune_config=TuneConfig(
                metric=state["metric"],
                mode=state["mode"],
                num_samples=state.get("num_samples", 1),
                max_failures_per_trial=state.get(
                    "max_failures_per_trial", 0),
                trial_resources=state.get("trial_resources", {"CPU": 1}),
            ),
            run_config=RunConfig(
                name=os.path.basename(experiment_path.rstrip("/")),
                storage_path=os.path.dirname(
                    experiment_path.rstrip("/")) or ".",
            ),
        )
        trials = [Trial.from_json(t) for t in state["trials"]]
        for t in trials:
            if not t.is_finished():
                t.status = PENDING  # re-run from last checkpoint
        tuner._restored_trials = trials
        return tuner

    def _experiment_dir(self) -> str:
        name = self.run_config.name or f"tune_{int(time.time())}"
        path = os.path.join(self.run_config.storage_path, name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> ResultGrid:
        import cloudpickle

        import ray_tpu as ray

        tc = self.tune_config
        exp_dir = self._experiment_dir()
        scheduler = tc.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", None) is None and tc.metric:
            scheduler.metric = tc.metric
            scheduler.mode = tc.mode
        payload = cloudpickle.dumps(self.trainable)
        resources = getattr(self.trainable, "_tune_resources",
                            tc.trial_resources)

        # --- build / restore trial set -------------------------------
        searcher = tc.search_alg
        searcher_exhausted = False
        if searcher is not None:
            # constructor-set metric/mode win; TuneConfig fills the gaps
            # (Searcher defaults both to None so tc.mode CAN apply)
            searcher.set_search_properties(
                getattr(searcher, "metric", None) or tc.metric,
                getattr(searcher, "mode", None) or tc.mode,
                self.param_space)
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif searcher is not None:
            # model-based search is SEQUENTIAL: trials are created
            # lazily (see _maybe_suggest below) so each suggestion is
            # informed by completions (reference: SearchGenerator)
            trials = []
        else:
            trials = [
                Trial(trial_id=f"t{i:05d}_{uuid.uuid4().hex[:6]}",
                      config=cfg)
                for i, cfg in enumerate(
                    search_mod.generate_variants(
                        self.param_space, tc.num_samples, tc.seed))
            ]
        for t in trials:
            scheduler.on_trial_add(t)

        max_concurrent = tc.max_concurrent_trials or (
            4 if searcher is not None else max(1, len(trials)))
        issued = len(trials)

        def _maybe_suggest():
            nonlocal issued, searcher_exhausted
            if searcher is None or searcher_exhausted:
                return
            active = sum(t.status in (RUNNING, PENDING) for t in trials)
            while issued < tc.num_samples and active < max_concurrent:
                tid = f"t{issued:05d}_{uuid.uuid4().hex[:6]}"
                cfg = searcher.suggest(tid)
                if cfg is None:
                    # exhausted: stop asking AND stop waiting for the
                    # never-to-arrive remaining samples (hang otherwise)
                    searcher_exhausted = True
                    return
                t = Trial(trial_id=tid, config=cfg)
                scheduler.on_trial_add(t)
                trials.append(t)
                issued += 1
                active += 1

        def _notify_searcher(t: Trial):
            if searcher is not None:
                try:
                    searcher.on_trial_complete(t.trial_id, t.last_result)
                except Exception:  # noqa: BLE001 — searcher bugs must
                    pass           # not kill the experiment loop
        actors: Dict[str, Any] = {}
        import numpy as np

        rng = np.random.default_rng(tc.seed)
        with open(os.path.join(exp_dir, "param_space.pkl"), "wb") as f:
            cloudpickle.dump(self.param_space, f)

        def persist():
            state = {
                "metric": tc.metric,
                "mode": tc.mode,
                "num_samples": tc.num_samples,
                "max_failures_per_trial": tc.max_failures_per_trial,
                "trial_resources": resources,
                "trials": [t.to_json() for t in trials],
            }
            tmp = os.path.join(exp_dir, ".state_tmp")
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, os.path.join(exp_dir,
                                         "experiment_state.json"))

        def trial_dir(t: Trial) -> str:
            d = os.path.join(exp_dir, t.trial_id)
            os.makedirs(d, exist_ok=True)
            return d

        def actor_options() -> dict:
            opts: Dict[str, Any] = {"max_restarts": 0}
            for key, val in resources.items():
                if key == "CPU":
                    opts["num_cpus"] = val
                elif key == "TPU":
                    opts["num_tpus"] = val
                else:
                    opts.setdefault("resources", {})[key] = val
            return opts

        ActorCls = ray.remote(_TrialActor)

        def start_trial(t: Trial):
            a = ActorCls.options(**actor_options()).remote(
                t.trial_id, trial_dir(t))
            a.run.remote(payload, t.config, t.checkpoint_path,
                         t.iteration)
            actors[t.trial_id] = a
            t.status = RUNNING
            t.start_time = time.time()

        def stop_actor(t: Trial):
            a = actors.pop(t.trial_id, None)
            if a is not None:
                try:
                    ray.kill(a)
                except Exception:
                    pass

        def save_trial_checkpoint(t: Trial, src_path: str) -> str:
            dest = os.path.join(trial_dir(t),
                                f"checkpoint_{t.iteration:06d}")
            if os.path.abspath(src_path) != dest:
                shutil.copytree(src_path, dest, dirs_exist_ok=True)
            return dest

        def append_history(t: Trial, metrics: dict):
            with open(os.path.join(trial_dir(t), "result.jsonl"),
                      "a") as f:
                f.write(json.dumps(metrics) + "\n")

        def handle_failure(t: Trial, err: str):
            stop_actor(t)
            t.num_failures += 1
            if t.num_failures <= tc.max_failures_per_trial:
                t.status = PENDING  # retry from last checkpoint
            else:
                t.status = ERROR
                t.error = err
                scheduler.on_trial_complete(t)
                _notify_searcher(t)

        # --- event loop ----------------------------------------------
        persist()
        try:
            while any(not t.is_finished() for t in trials) or (
                searcher is not None and not searcher_exhausted
                and issued < tc.num_samples
            ):
                _maybe_suggest()
                # launch pending trials up to the concurrency cap
                running = [t for t in trials if t.status == RUNNING]
                for t in trials:
                    if (t.status == PENDING
                            and len(running) < max_concurrent):
                        start_trial(t)
                        running.append(t)
                dirty = False
                for t in list(running):
                    a = actors.get(t.trial_id)
                    if a is None:
                        continue
                    try:
                        p = ray.get(a.poll.remote(), timeout=60)
                    except ray.RayError as e:
                        handle_failure(t, f"trial actor died: {e}")
                        dirty = True
                        continue
                    decision = CONTINUE
                    for rep in p["reports"]:
                        t.iteration = rep["metrics"].get(
                            "training_iteration", t.iteration + 1)
                        t.last_result = rep["metrics"]
                        append_history(t, rep["metrics"])
                        if rep["checkpoint_path"]:
                            t.checkpoint_path = save_trial_checkpoint(
                                t, rep["checkpoint_path"])
                        decision = scheduler.on_result(
                            t, rep["metrics"], trials)
                        dirty = True
                        if decision != CONTINUE:
                            break
                    if isinstance(decision, tuple) and \
                            decision[0] == EXPLOIT:
                        source = decision[1]
                        stop_actor(t)
                        if source.checkpoint_path:
                            t.checkpoint_path = save_trial_checkpoint(
                                t, source.checkpoint_path)
                        explore = getattr(scheduler, "explore", None)
                        t.config = (
                            explore(source.config, self.param_space, rng)
                            if explore is not None
                            else search_mod.perturb_config(
                                source.config, self.param_space, rng))
                        if searcher is not None:
                            # the trial now runs a DIFFERENT config: a
                            # model-based searcher must not credit the
                            # eventual score to its stale suggestion
                            searcher.on_trial_config_update(
                                t.trial_id, t.config)
                        t.status = PENDING  # restart exploited trial
                        dirty = True
                        continue
                    if decision in (STOP, COMPLETE):
                        stop_actor(t)
                        t.status = TERMINATED
                        t.stopped_early = decision == STOP
                        scheduler.on_trial_complete(t)
                        _notify_searcher(t)
                        dirty = True
                        continue
                    if p["error"]:
                        handle_failure(t, p["error"])
                        dirty = True
                    elif p["done"]:
                        stop_actor(t)
                        t.status = TERMINATED
                        scheduler.on_trial_complete(t)
                        _notify_searcher(t)
                        dirty = True
                if dirty:
                    persist()
                time.sleep(0.05)
        finally:
            for t in trials:
                stop_actor(t)
            persist()

        results = [
            TrialResult(
                trial_id=t.trial_id,
                config=t.config,
                metrics=t.last_result,
                checkpoint=Checkpoint(t.checkpoint_path)
                if t.checkpoint_path else None,
                error=t.error,
                path=os.path.join(exp_dir, t.trial_id),
            )
            for t in trials
        ]
        grid = ResultGrid(results, exp_dir)
        grid._metric = tc.metric
        grid._mode = tc.mode
        return grid
