"""ray_tpu.tune — hyperparameter tuning over trial actors.

Reference: python/ray/tune (Tuner tuner.py:43, TuneController
execution/tune_controller.py:68, ASHA schedulers/async_hyperband.py,
PBT schedulers/pbt.py, search spaces search/sample.py).
"""
from ..train.checkpoint import Checkpoint  # noqa: F401
from .schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (  # noqa: F401
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from .trial import Trial  # noqa: F401
from .tuner import (  # noqa: F401
    ResultGrid,
    TuneConfig,
    TuneError,
    Tuner,
    get_checkpoint,
    get_trial_dir,
    get_trial_id,
    report,
    with_resources,
)
