"""Search spaces + variant generation.

Reference: python/ray/tune/search/sample.py (Domain/Categorical/Float/
Integer, grid_search) and tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws).
TPU-native redesign: plain-Python domains with a seeded numpy RNG; no
external searcher deps (optuna/hyperopt are cloud-side concerns).
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    # PBT explore support: perturb a current value within the domain.
    def perturb(self, value: Any, rng: np.random.Generator) -> Any:
        return self.sample(rng)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(len(self.categories)))]

    def perturb(self, value, rng):
        # move to a neighboring category (reference pbt.py explore:
        # resample from the distribution)
        return self.sample(rng)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = float(lower), float(upper), log

    def sample(self, rng):
        if self.log:
            lo, hi = np.log(self.lower), np.log(self.upper)
            return float(np.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.lower, self.upper))

    def perturb(self, value, rng):
        # reference pbt.py:explore — multiply by 0.8 or 1.2, clip
        factor = 1.2 if rng.random() < 0.5 else 0.8
        return float(np.clip(value * factor, self.lower, self.upper))


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))

    def perturb(self, value, rng):
        factor = 1.2 if rng.random() < 0.5 else 0.8
        return int(np.clip(round(value * factor), self.lower,
                           self.upper - 1))


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


# --- public constructors (match ray.tune names) -----------------------
def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _split_space(space: Dict[str, Any], prefix=()):
    """Walk a (possibly nested) param space, yielding (path, spec)."""
    for key, val in space.items():
        path = prefix + (key,)
        if isinstance(val, dict) and not _is_grid(val):
            yield from _split_space(val, path)
        else:
            yield path, val


def _set_path(cfg: dict, path, value):
    node = cfg
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> Iterator[Dict[str, Any]]:
    """Reference: BasicVariantGenerator semantics — the full grid
    cross-product is repeated ``num_samples`` times, with random domains
    re-drawn per variant."""
    rng = np.random.default_rng(seed)
    entries = list(_split_space(param_space))
    grid_paths = [(p, v["grid_search"]) for p, v in entries if _is_grid(v)]
    grids = [vals for _, vals in grid_paths] or [[None]]

    for _ in range(num_samples):
        for combo in itertools.product(*grids):
            cfg: Dict[str, Any] = {}
            for path, spec in entries:
                if _is_grid(spec):
                    continue
                if isinstance(spec, Domain):
                    _set_path(cfg, path, spec.sample(rng))
                else:
                    _set_path(cfg, path, spec)
            if grid_paths:
                for (path, _), val in zip(grid_paths, combo):
                    _set_path(cfg, path, val)
            yield cfg


def perturb_config(
    config: Dict[str, Any],
    param_space: Dict[str, Any],
    rng: np.random.Generator,
) -> Dict[str, Any]:
    """PBT explore step: perturb every Domain-valued hyperparameter
    (reference: tune/schedulers/pbt.py _explore)."""
    import copy

    # deep copy: perturbing a nested key must not mutate the source
    # trial's config
    new = copy.deepcopy(config)
    for path, spec in _split_space(param_space):
        if isinstance(spec, Domain):
            node = new
            ok = True
            for key in path[:-1]:
                node = node.get(key)
                if not isinstance(node, dict):
                    ok = False
                    break
            if ok and path[-1] in node:
                node[path[-1]] = spec.perturb(node[path[-1]], rng)
    return new


# ---------------------------------------------------------------------------
# Model-based search (reference: tune/search/searcher.py Searcher API;
# tune/search/optuna/optuna_search.py wraps optuna's TPE sampler — here
# the TPE is native, zero-dependency, over the same Domain param space)
# ---------------------------------------------------------------------------


class Searcher:
    """Sequential config suggester: the tuner asks for one config per
    new trial and reports completions back, so later suggestions are
    informed by earlier results."""

    def set_search_properties(self, metric: Optional[str], mode: str,
                              space: Dict[str, Any]) -> None:
        self.metric = metric
        self.mode = mode
        self.space = space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def on_trial_config_update(self, trial_id: str,
                               config: Dict[str, Any]) -> None:
        """A scheduler replaced the trial's config (PBT exploit): the
        model must credit the eventual result to what actually ran."""
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the model behind optuna's
    default sampler and hyperopt): observations split into a GOOD top
    quantile and the rest; each numeric dimension gets a Parzen
    (Gaussian-kernel) density for both sets, categoricals get smoothed
    count weights. Candidates are drawn from the good model and ranked
    by the density ratio l(x)/g(x) — the next trial lands where good
    configs are dense and bad ones are not.

    Independent per-dimension models, like hyperopt's default; log
    domains modeled in log space."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 64, explore_eps: float = 0.2,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        # ε-mixing: this fraction of suggestions are pure prior draws —
        # the density-ratio argmax alone can lock onto an early local
        # cluster and never probe the rest of the domain
        self.explore_eps = explore_eps
        self.rng = np.random.default_rng(seed)
        self.space: Dict[str, Any] = {}
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Tuple[Dict[str, Any], float]] = []

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        for path, spec in _split_space(space):
            if _is_grid(spec):
                raise ValueError(
                    "TPESearcher does not combine with grid_search; "
                    "use choice() instead")

    # -- observation bookkeeping --------------------------------------
    def on_trial_complete(self, trial_id, result):
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = (float(value) if (self.mode or "max") == "max"
                 else -float(value))
        self._obs.append((cfg, score))

    def on_trial_config_update(self, trial_id, config):
        if trial_id in self._pending:
            self._pending[trial_id] = config

    # -- suggestion ---------------------------------------------------
    def suggest(self, trial_id):
        if (len(self._obs) < self.n_initial
                or self.rng.random() < self.explore_eps):
            cfg = next(generate_variants(
                self.space, 1,
                seed=int(self.rng.integers(2**31 - 1))))
        else:
            cfg = self._tpe_config()
        self._pending[trial_id] = cfg
        return cfg

    def _tpe_config(self) -> Dict[str, Any]:
        ranked = sorted(self._obs, key=lambda p: p[1], reverse=True)
        n_good = max(1, int(np.ceil(self.gamma * len(ranked))))
        good = [c for c, _s in ranked[:n_good]]
        bad = [c for c, _s in ranked[n_good:]] or good
        cfg: Dict[str, Any] = {}
        for path, spec in _split_space(self.space):
            if isinstance(spec, Categorical):
                _set_path(cfg, path, self._tpe_categorical(
                    path, spec, good, bad))
            elif isinstance(spec, (Float, Integer)):
                _set_path(cfg, path, self._tpe_numeric(
                    path, spec, good, bad))
            elif isinstance(spec, Domain):
                _set_path(cfg, path, spec.sample(self.rng))
            else:
                _set_path(cfg, path, spec)
        return cfg

    @staticmethod
    def _get_path(cfg, path):
        node = cfg
        for k in path:
            node = node[k]
        return node

    def _tpe_categorical(self, path, spec, good, bad):
        cats = list(spec.categories)
        prior = 1.0  # Laplace smoothing

        def weights(obs):
            w = np.full(len(cats), prior)
            for c in obs:
                try:
                    w[cats.index(self._get_path(c, path))] += 1.0
                except (ValueError, KeyError):
                    pass
            return w / w.sum()

        ratio = weights(good) / weights(bad)
        # sample ∝ good-weight, tilted by the ratio (argmax over the
        # tilted distribution == pick the best-looking category while
        # keeping exploration mass on near-ties)
        p = weights(good) * ratio
        p = p / p.sum()
        return cats[int(self.rng.choice(len(cats), p=p))]

    def _tpe_numeric(self, path, spec, good, bad):
        log = isinstance(spec, Float) and spec.log
        lo, hi = float(spec.lower), float(spec.upper)
        tlo, thi = (np.log(lo), np.log(hi)) if log else (lo, hi)

        def xs(obs):
            vals = []
            for c in obs:
                try:
                    v = float(self._get_path(c, path))
                except (KeyError, TypeError):
                    continue
                vals.append(np.log(v) if log else v)
            return np.asarray(vals) if vals else np.asarray([
                (tlo + thi) / 2.0])

        gx, bx = xs(good), xs(bad)
        width = thi - tlo
        # Scott-style bandwidth with a floor so early models stay wide
        def bw(x):
            s = float(np.std(x)) if len(x) > 1 else width / 4.0
            return max(s * len(x) ** (-1 / 5), width / 20.0)

        gbw, bbw = bw(gx), bw(bx)
        # hyperopt-style uniform PRIOR kernel mixed into BOTH densities
        # (a wide Gaussian at the domain midpoint): keeps tail mass in
        # l(x) so the search can jump out of an early cluster, and
        # floors g(x) so the ratio can't diverge at the edges
        mid = (tlo + thi) / 2.0
        gcent = np.append(gx, mid)
        ghs = np.append(np.full(len(gx), gbw), width)
        bcent = np.append(bx, mid)
        bhs = np.append(np.full(len(bx), bbw), width)

        def logpdf(x, centers, hs):
            d = (x[:, None] - centers[None, :]) / hs[None, :]
            k = -0.5 * d * d - np.log(hs[None, :] * np.sqrt(2 * np.pi))
            m = k.max(axis=1, keepdims=True)
            return (m[:, 0] + np.log(
                np.exp(k - m).sum(axis=1) / len(centers)))

        # candidates: mostly from the good mixture, a quarter from the
        # prior (uniform over the domain) for exploration
        n_prior = max(1, self.n_candidates // 4)
        n_good = self.n_candidates - n_prior
        idx = self.rng.integers(0, len(gx), size=n_good)
        cand = np.concatenate([
            gx[idx] + self.rng.normal(0.0, gbw, n_good),
            self.rng.uniform(tlo, thi, n_prior),
        ])
        cand = np.clip(cand, tlo, thi)
        score = logpdf(cand, gcent, ghs) - logpdf(cand, bcent, bhs)
        best = float(cand[int(np.argmax(score))])
        value = float(np.exp(best)) if log else best
        if isinstance(spec, Integer):
            return int(np.clip(round(value), spec.lower, spec.upper - 1))
        return float(np.clip(value, lo, hi))
