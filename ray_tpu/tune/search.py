"""Search spaces + variant generation.

Reference: python/ray/tune/search/sample.py (Domain/Categorical/Float/
Integer, grid_search) and tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws).
TPU-native redesign: plain-Python domains with a seeded numpy RNG; no
external searcher deps (optuna/hyperopt are cloud-side concerns).
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    # PBT explore support: perturb a current value within the domain.
    def perturb(self, value: Any, rng: np.random.Generator) -> Any:
        return self.sample(rng)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(len(self.categories)))]

    def perturb(self, value, rng):
        # move to a neighboring category (reference pbt.py explore:
        # resample from the distribution)
        return self.sample(rng)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = float(lower), float(upper), log

    def sample(self, rng):
        if self.log:
            lo, hi = np.log(self.lower), np.log(self.upper)
            return float(np.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.lower, self.upper))

    def perturb(self, value, rng):
        # reference pbt.py:explore — multiply by 0.8 or 1.2, clip
        factor = 1.2 if rng.random() < 0.5 else 0.8
        return float(np.clip(value * factor, self.lower, self.upper))


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))

    def perturb(self, value, rng):
        factor = 1.2 if rng.random() < 0.5 else 0.8
        return int(np.clip(round(value * factor), self.lower,
                           self.upper - 1))


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


# --- public constructors (match ray.tune names) -----------------------
def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _split_space(space: Dict[str, Any], prefix=()):
    """Walk a (possibly nested) param space, yielding (path, spec)."""
    for key, val in space.items():
        path = prefix + (key,)
        if isinstance(val, dict) and not _is_grid(val):
            yield from _split_space(val, path)
        else:
            yield path, val


def _set_path(cfg: dict, path, value):
    node = cfg
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> Iterator[Dict[str, Any]]:
    """Reference: BasicVariantGenerator semantics — the full grid
    cross-product is repeated ``num_samples`` times, with random domains
    re-drawn per variant."""
    rng = np.random.default_rng(seed)
    entries = list(_split_space(param_space))
    grid_paths = [(p, v["grid_search"]) for p, v in entries if _is_grid(v)]
    grids = [vals for _, vals in grid_paths] or [[None]]

    for _ in range(num_samples):
        for combo in itertools.product(*grids):
            cfg: Dict[str, Any] = {}
            for path, spec in entries:
                if _is_grid(spec):
                    continue
                if isinstance(spec, Domain):
                    _set_path(cfg, path, spec.sample(rng))
                else:
                    _set_path(cfg, path, spec)
            if grid_paths:
                for (path, _), val in zip(grid_paths, combo):
                    _set_path(cfg, path, val)
            yield cfg


def perturb_config(
    config: Dict[str, Any],
    param_space: Dict[str, Any],
    rng: np.random.Generator,
) -> Dict[str, Any]:
    """PBT explore step: perturb every Domain-valued hyperparameter
    (reference: tune/schedulers/pbt.py _explore)."""
    import copy

    # deep copy: perturbing a nested key must not mutate the source
    # trial's config
    new = copy.deepcopy(config)
    for path, spec in _split_space(param_space):
        if isinstance(spec, Domain):
            node = new
            ok = True
            for key in path[:-1]:
                node = node.get(key)
                if not isinstance(node, dict):
                    ok = False
                    break
            if ok and path[-1] in node:
                node[path[-1]] = spec.perturb(node[path[-1]], rng)
    return new
