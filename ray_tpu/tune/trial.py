"""Trial state machine.

Reference: python/ray/tune/experiment/trial.py (Trial) — pared to the
fields the controller and schedulers actually use, JSON-serializable for
experiment checkpoint/resume.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    iteration: int = 0
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    num_failures: int = 0
    start_time: float = 0.0
    stopped_early: bool = False
    # PBT bookkeeping
    last_perturb_iter: int = 0

    def to_json(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "iteration": self.iteration,
            "checkpoint_path": self.checkpoint_path,
            "error": self.error,
            "num_failures": self.num_failures,
            "stopped_early": self.stopped_early,
            "last_perturb_iter": self.last_perturb_iter,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Trial":
        t = cls(trial_id=d["trial_id"], config=d["config"])
        t.status = d.get("status", PENDING)
        t.last_result = d.get("last_result", {})
        t.iteration = d.get("iteration", 0)
        t.checkpoint_path = d.get("checkpoint_path")
        t.error = d.get("error")
        t.num_failures = d.get("num_failures", 0)
        t.stopped_early = d.get("stopped_early", False)
        t.last_perturb_iter = d.get("last_perturb_iter", 0)
        return t

    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric(self, name: str, default=None):
        return self.last_result.get(name, default)
