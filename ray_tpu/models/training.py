"""Training step construction: pjit over the mesh with logical shardings.

The reference's equivalent moment is torch DDP/FSDP wrap + optimizer step
inside Ray Train workers (train/torch/train_loop_utils.py prepare_model);
here the whole step (fwd + bwd + optimizer) is ONE compiled XLA program
whose collectives XLA derives from the sharding annotations — compile once,
stream batches (the compiled-graph analogue: SURVEY §2.3 aDAG row).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..parallel.sharding import logical_sharding, resolve_spec
from .llama import LlamaConfig, init_params, loss_fn, param_logical_axes


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def state_shardings(cfg: LlamaConfig, mesh, optimizer) -> TrainState:
    """Sharding pytree for TrainState. Optimizer moments are zeros_like the
    params inside jit, so GSPMD propagates the param shardings to them —
    opt_state uses auto (None) shardings rather than a hand-built tree."""
    axes = param_logical_axes(cfg)
    param_sh = jax.tree_util.tree_map(
        lambda a: logical_sharding(mesh, a),
        axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    replicated = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return TrainState(params=param_sh, opt_state=None, step=replicated)


def make_train_step(
    cfg: LlamaConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
) -> Tuple[Callable, Callable]:
    """Returns (init_fn, step_fn), both jitted over the mesh.

    init_fn(seed) -> TrainState sharded per the logical rules.
    step_fn(state, tokens[B, S+1]) -> (state, metrics dict)
    """
    optimizer = optimizer or make_optimizer()
    shardings = state_shardings(cfg, mesh, optimizer)
    batch_sharding = logical_sharding(mesh, ("batch", None))

    def init(seed: int) -> TrainState:
        params = init_params(cfg, jax.random.PRNGKey(seed))
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    init_jit = jax.jit(init, out_shardings=shardings, static_argnums=())

    def step(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, mesh=mesh)
        )(state.params)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params, opt_state, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state.step}

    step_jit = jax.jit(
        step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )
    return init_jit, step_jit
