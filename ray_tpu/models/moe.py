"""Mixture-of-Experts feed-forward with expert parallelism.

Reference counterpart: none — the reference passes MoE models through to
vLLM via engine_kwargs and places them with PGs (SURVEY §2.3 EP row).
This is the TPU-native design: GShard/Switch-style capacity-based top-k
routing expressed as dense einsums, with the expert axis of both weights
and dispatched activations sharded over the ``expert`` mesh axis — XLA
lowers the dispatch/combine einsums to all-to-alls over ICI. Dense
one-hot dispatch (not a sorted ragged kernel) is the right first
implementation on TPU: it is MXU-shaped, fully static, and fuses; a
Pallas sorted-dispatch kernel is a later optimization, not a semantic
change.

Shapes: tokens T = B*S, experts E, capacity C = ceil(capacity_factor *
k * T / E). Tokens routed beyond an expert's capacity are dropped (their
combine weight is zero) — standard Switch behavior.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, k: int,
                    capacity_factor: float) -> int:
    return max(1, int(capacity_factor * k * n_tokens / n_experts))


def moe_ffn(
    x: jax.Array,  # [T, d] tokens
    router: jax.Array,  # [d, E]
    we1: jax.Array,  # [E, d, f]
    we3: jax.Array,  # [E, d, f]
    we2: jax.Array,  # [E, f, d]
    k: int,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (y [T, d], aux_loss scalar f32).

    aux_loss is the GShard load-balancing loss: E * sum_e(frac_tokens_e *
    frac_router_prob_e), minimized at uniform routing.
    """
    T, d = x.shape
    E = router.shape[-1]
    C = expert_capacity(T, E, k, capacity_factor)

    logits = (x.astype(jnp.float32) @ router.astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [T, k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9
    )

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, k, E]

    # position of each (token, slot) within its expert's capacity buffer:
    # fill slot-0 choices first, then slot-1, ... (GShard ordering)
    positions = []
    filled = jnp.zeros((E,), dtype=jnp.float32)
    for slot in range(k):
        oh = onehot[:, slot]  # [T, E]
        pos_in_e = jnp.cumsum(oh, axis=0) - 1.0 + filled[None, :]
        filled = filled + oh.sum(axis=0)
        positions.append((pos_in_e * oh).sum(-1))  # [T]
    pos = jnp.stack(positions, axis=1)  # [T, k]
    keep = (pos < C).astype(jnp.float32)  # capacity drop
    gate_vals = gate_vals * keep

    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32)  # [T, k, C]
    disp = onehot[:, :, :, None] * pos_oh[:, :, None, :] \
        * keep[:, :, None, None]  # [T, k, E, C]
    dispatch = disp.sum(axis=1)  # [T, E, C] (0/1)
    combine = (gate_vals[:, :, None, None] * disp).sum(axis=1)  # [T, E, C]

    # dispatch: [E, C, d] — the einsum XLA turns into an all-to-all when
    # E is sharded over the expert mesh axis
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, x.astype(jnp.float32)
    ).astype(x.dtype)
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, we1).astype(jnp.float32)
    ).astype(x.dtype)
    up = jnp.einsum("ecd,edf->ecf", expert_in, we3)
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, we2)  # [E, C, d]
    y = jnp.einsum(
        "tec,ecd->td", combine, expert_out.astype(jnp.float32)
    ).astype(x.dtype)

    # load-balance aux loss (Switch eq.4): fraction of tokens routed to e
    # (slot-0 argmax) x mean router prob for e
    frac_tokens = onehot[:, 0].mean(axis=0)  # [E]
    frac_probs = probs.mean(axis=0)  # [E]
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return y, aux


def init_moe_layer(key, n_layers: int, dim: int, ffn_dim: int,
                   n_experts: int, dtype) -> Dict[str, Any]:
    """Stacked MoE params [L, E, ...] for the scanned layer tree."""
    ks = jax.random.split(key, 4)

    def dense(k, fan_in, *shape):
        return (
            jax.random.normal(k, shape, dtype=jnp.float32)
            * (fan_in ** -0.5)
        ).astype(dtype)

    L, E, d, f = n_layers, n_experts, dim, ffn_dim
    return {
        "router": dense(ks[0], d, L, d, E).astype(jnp.float32),
        "we1": dense(ks[1], d, L, E, d, f),
        "we3": dense(ks[2], d, L, E, d, f),
        "we2": dense(ks[3], f, L, E, f, d),
    }


def moe_logical_axes() -> Dict[str, Any]:
    return {
        "router": (None, "embed", None),
        "we1": (None, "experts", "embed", "mlp"),
        "we3": (None, "experts", "embed", "mlp"),
        "we2": (None, "experts", "mlp", "embed"),
    }
