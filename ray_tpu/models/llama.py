"""Llama-family transformer, TPU-first.

Design (vs. the reference, which delegates all modeling to torch/vLLM):
  - functional: params are a pytree of arrays; a parallel tree of logical
    axis names drives sharding (parallel/sharding.py) — dp/fsdp/tp/sp are
    a rules-table change, not a model change.
  - layers are stacked and scanned (lax.scan) for O(1) compile time with
    per-layer rematerialization (jax.checkpoint) to trade FLOPs for HBM.
  - bfloat16 params/activations, f32 RMSNorm/softmax/logits — the MXU-
    friendly mix.
  - attention is pluggable: "flash" (ops/attention.py Pallas kernel on
    TPU), "ring" / "ulysses" (parallel/) when the mesh has a seq axis.

Presets cover Llama-3 8B (the flagship bench model, BASELINE.md
north-star), Llama-2 7B, and tiny/debug sizes.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import flash_attention


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    attn: str = "flash"  # flash | ring | ulysses
    remat: bool = True
    # remat policy: "full" recomputes everything (min HBM);
    # "dots" saves matmul outputs and recomputes elementwise/norms only
    # (≈⅓ less recompute FLOPs when activations fit); "none" via
    # remat=False
    remat_policy: str = "full"
    # chunked cross-entropy: sequence-chunk size for the loss (0 = one
    # full [B, S, vocab] logits tensor). Chunking keeps only chunk-wide
    # f32 logits live (recomputed in bwd), trading one extra vocab
    # matmul for ~1 GiB peak HBM at the flagship size.
    ce_chunk: int = 0
    # MoE (0 = dense). Mixtral-style top-k routing; experts shard over
    # the "expert" mesh axis (models/moe.py).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # pipeline parallelism: microbatches per step when the mesh has a
    # pipe axis > 1 (0 = pick 2*pipe automatically)
    pp_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # --- presets -----------------------------------------------------
    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_70b(**kw) -> "LlamaConfig":
        return LlamaConfig(
            dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
            ffn_dim=28672, **kw,
        )

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, ffn_dim=11008, rope_theta=10000.0, **kw,
        )

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """CPU-testable size."""
        defaults = dict(
            vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=256, dtype=jnp.float32, remat=False,
        )
        defaults.update(kw)
        return LlamaConfig(**defaults)

    def replace(self, **kw) -> "LlamaConfig":
        return dataclasses.replace(self, **kw)

    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        hd = self.head_dim
        per_layer = (
            d * self.n_heads * hd  # wq
            + 2 * d * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * d  # wo
            + 3 * d * f  # w1, w2, w3
            + 2 * d  # norms
        )
        emb = v * d * (1 if self.tie_embeddings else 2)
        return per_layer * self.n_layers + emb + d


# ---------------------------------------------------------------------------
# init + logical axes
# ---------------------------------------------------------------------------
def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer param tree (leading axis = layers, scanned)."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    d, hd = cfg.dim, cfg.head_dim
    L = cfg.n_layers

    def norm_init(*shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def dense(key, fan_in, *shape):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32)
            * (fan_in ** -0.5)
        ).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "wq": dense(ks[0], d, L, d, cfg.n_heads * hd),
        "wk": dense(ks[1], d, L, d, cfg.n_kv_heads * hd),
        "wv": dense(ks[2], d, L, d, cfg.n_kv_heads * hd),
        "wo": dense(ks[3], cfg.n_heads * hd, L, cfg.n_heads * hd, d),
        "attn_norm": norm_init(L, d),
        "mlp_norm": norm_init(L, d),
    }
    if cfg.n_experts > 0:
        from .moe import init_moe_layer

        layers.update(init_moe_layer(
            ks[7], L, d, cfg.ffn_dim, cfg.n_experts, cfg.dtype
        ))
    else:
        layers.update({
            "w1": dense(ks[4], d, L, d, cfg.ffn_dim),
            "w3": dense(ks[5], d, L, d, cfg.ffn_dim),
            "w2": dense(ks[6], cfg.ffn_dim, L, cfg.ffn_dim, d),
        })
    params = {
        "tok_embed": (
            jax.random.normal(k_emb, (cfg.vocab_size, d), dtype=jnp.float32)
            * 0.02
        ).astype(cfg.dtype),
        "layers": layers,
        "final_norm": norm_init(d),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_out, d, d, cfg.vocab_size)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Same-structure tree of logical axis tuples (the leading "layers"
    axis maps to the pipe mesh axis — unsharded unless pipe > 1)."""
    layers = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "attn_norm": ("layers", "norm"),
        "mlp_norm": ("layers", "norm"),
    }
    if cfg.n_experts > 0:
        from .moe import moe_logical_axes

        for name, axes in moe_logical_axes().items():
            layers[name] = ("layers",) + axes[1:]
    else:
        layers.update({
            "w1": ("layers", "embed", "mlp"),
            "w3": ("layers", "embed", "mlp"),
            "w2": ("layers", "mlp", "embed"),
        })
    axes = {
        "tok_embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * w.astype(jnp.float32)).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; positions: [S] or [B, S]."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    if positions.ndim == 1:
        angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
        angles = angles[None, :, None, :]  # [1, S, 1, D/2]
    else:
        angles = positions[..., None].astype(jnp.float32) * freqs
        angles = angles[:, :, None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention_dispatch(cfg: LlamaConfig, q, k, v, mesh, positions):
    if cfg.attn in ("ring", "ulysses") and mesh is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.ring_attention import ring_attention
        from ..parallel.ulysses import ulysses_attention

        fn = ring_attention if cfg.attn == "ring" else ulysses_attention
        spec_q = P(("data", "fsdp"), "seq", "tensor", None)
        spec_kv = P(("data", "fsdp"), "seq", "tensor", None)
        return shard_map(
            partial(fn, axis_name="seq", causal=True),
            mesh=mesh,
            in_specs=(spec_q, spec_kv, spec_kv),
            out_specs=spec_q,
        )(q, k, v)
    return flash_attention(q, k, v, causal=True)


def _layer(cfg: LlamaConfig, x, lp, mesh, positions):
    """One transformer block; returns (x, aux_loss)."""
    B, S, d = x.shape
    hd = cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, cfg.n_heads, hd)
    k = (h @ lp["wk"]).reshape(B, S, cfg.n_kv_heads, hd)
    v = (h @ lp["wv"]).reshape(B, S, cfg.n_kv_heads, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = _attention_dispatch(cfg, q, k, v, mesh, positions)
    attn = attn.astype(x.dtype).reshape(B, S, cfg.n_heads * hd)
    x = x + attn @ lp["wo"]
    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from .moe import moe_ffn

        y, aux = moe_ffn(
            h.reshape(B * S, d), lp["router"], lp["we1"], lp["we3"],
            lp["we2"], cfg.n_experts_per_tok, cfg.capacity_factor,
        )
        return x + y.reshape(B, S, d), aux
    gate = jax.nn.silu((h @ lp["w1"]).astype(jnp.float32)).astype(x.dtype)
    x = x + (gate * (h @ lp["w3"])) @ lp["w2"]
    return x, jnp.zeros((), jnp.float32)


def forward(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    mesh=None,
    return_aux: bool = False,
    return_hidden: bool = False,
):
    """Returns logits [B, S, vocab] (f32); with return_aux, also the
    summed MoE load-balance aux loss. return_hidden skips the vocab
    projection and returns (final-norm hidden states [B, S, d], aux) —
    the chunked-CE loss path projects per chunk instead."""
    B, S = tokens.shape
    x = params["tok_embed"][tokens]  # [B, S, d]
    positions = jnp.arange(S)

    layer_fn = partial(_layer, cfg, mesh=mesh, positions=positions)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            layer_fn = jax.checkpoint(
                layer_fn,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
            )
        else:
            layer_fn = jax.checkpoint(layer_fn)

    pipe = 1
    if mesh is not None:
        pipe = dict(zip(mesh.axis_names, mesh.devices.shape)).get("pipe", 1)
    if pipe > 1:
        # GPipe-schedule SPMD over the pipe axis (parallel/pipeline.py).
        # MoE aux loss is not collected on this path (stage outputs carry
        # activations only).
        from ..parallel.pipeline import pipeline_apply

        M = cfg.pp_microbatches
        if not M:
            # auto-pick: largest divisor of B up to 2*pipe
            M = max(m for m in range(1, min(B, 2 * pipe) + 1)
                    if B % m == 0)
        x, aux = pipeline_apply(
            mesh, params["layers"], x, layer_fn, M, with_aux=True
        )
    else:
        def body(carry, lp):
            x, aux = carry
            x, a = layer_fn(x, lp)
            return (x, aux + a), None

        (x, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"]
        )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        return x, aux
    head = (
        params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    )
    # bf16 operands + f32 MXU accumulation: same f32 logits out, ~4x
    # the matmul rate of f32 operands (the vocab projection is ~7% of
    # forward FLOPs — at f32 rate it costs ~4x that share of step time)
    logits = jax.lax.dot_general(
        x.astype(cfg.dtype), head.astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if return_aux:
        return logits, aux
    return logits


# ---------------------------------------------------------------------------
# KV-cache inference path (serving; reference delegates this to vLLM —
# here it is native: SURVEY §2.4 Ray LLM row)
# ---------------------------------------------------------------------------
def init_cache(cfg: LlamaConfig, batch_size: int, max_seq: int
               ) -> Dict[str, jax.Array]:
    shape = (cfg.n_layers, batch_size, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def _cached_attention(q, k_cache, v_cache, positions, scale):
    """q: [B,T,H,D]; caches: [B,S,Hkv,D]; positions: [B,T] global q pos.
    Attends to kv_pos <= q_pos (cache rows beyond each row's filled length
    hold zeros but are masked out)."""
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32),
        k_cache.astype(jnp.float32),
    ) * scale
    kv_pos = jnp.arange(S)[None, None, None, :]  # [1,1,1,S]
    mask = kv_pos <= positions[:, None, :, None]  # [B,1,T,S]
    scores = jnp.where(mask, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhts,bshd->bthd", p, v_cache.astype(jnp.float32)
    ).astype(q.dtype)


def forward_cached(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, T] new tokens for each slot
    cache: Dict[str, jax.Array],
    start_pos: jax.Array,  # [B] current filled length per slot
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Incremental forward: writes K/V for the new tokens into the cache,
    returns (logits [B, T, vocab], updated cache). Prefill: T = prompt
    length; decode: T = 1. jit-stable for fixed (B, T)."""
    B, T = tokens.shape
    hd = cfg.head_dim
    x = params["tok_embed"][tokens]
    positions = start_pos[:, None] + jnp.arange(T)[None, :]  # [B, T]
    scale = hd ** -0.5

    def write_rows(cache_l, new):
        # per-row dynamic update at row-specific offsets
        def upd(c_b, n_b, p_b):
            return jax.lax.dynamic_update_slice_in_dim(c_b, n_b, p_b, axis=0)

        return jax.vmap(upd)(cache_l, new, start_pos)

    def layer(x, scanned):
        lp, k_cache_l, v_cache_l = scanned
        h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(B, T, cfg.n_heads, hd)
        k = (h @ lp["wk"]).reshape(B, T, cfg.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(B, T, cfg.n_kv_heads, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        k_cache_l = write_rows(k_cache_l, k.astype(k_cache_l.dtype))
        v_cache_l = write_rows(v_cache_l, v.astype(v_cache_l.dtype))
        attn = _cached_attention(q, k_cache_l, v_cache_l, positions, scale)
        x = x + attn.reshape(B, T, cfg.n_heads * hd) @ lp["wo"]
        h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.n_experts > 0:
            from .moe import moe_ffn

            y, _ = moe_ffn(
                h.reshape(B * T, cfg.dim), lp["router"], lp["we1"],
                lp["we3"], lp["we2"], cfg.n_experts_per_tok,
                cfg.capacity_factor,
            )
            x = x + y.reshape(B, T, cfg.dim)
        else:
            gate = jax.nn.silu(
                (h @ lp["w1"]).astype(jnp.float32)
            ).astype(x.dtype)
            x = x + (gate * (h @ lp["w3"])) @ lp["w2"]
        return x, (k_cache_l, v_cache_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"])
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (
        params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    )
    logits = x.astype(jnp.float32) @ head.astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# Paged KV-cache path (serving; reference capability: vLLM PagedAttention,
# consumed as a black box by ray.llm — here native, ops/paged_attention.py)
# ---------------------------------------------------------------------------
def init_paged_cache(cfg: LlamaConfig, num_pages: int, page_size: int
                     ) -> Dict[str, jax.Array]:
    # head-major layout [L, Hkv, P, ps, D]: every Pallas block spans the
    # full trailing (page_size, head_dim) tile (ops/paged_attention.py)
    shape = (cfg.n_layers, cfg.n_kv_heads, num_pages, page_size,
             cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def forward_paged_decode(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: jax.Array,      # [B, 1] next token per sequence
    pages: Dict[str, jax.Array],
    page_table: jax.Array,  # [B, n_pages_per_seq] int32
    lengths: jax.Array,     # [B] current filled KV length per sequence
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One decode step over paged KV: writes the new token's K/V into
    each sequence's current page, attends over the page table. Returns
    (logits [B, vocab], updated pages)."""
    from ..ops.paged_attention import paged_attention

    B = tokens.shape[0]
    hd = cfg.head_dim
    ps = pages["k"].shape[3]
    x = params["tok_embed"][tokens]  # [B, 1, d]
    positions = lengths[:, None]  # [B, 1]
    batch_ix = jnp.arange(B)
    page_ix = page_table[batch_ix, lengths // ps]  # [B] physical page
    offset = lengths % ps

    def layer(x, scanned):
        lp, k_pages_l, v_pages_l = scanned
        h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(B, 1, cfg.n_heads, hd)
        k = (h @ lp["wk"]).reshape(B, 1, cfg.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(B, 1, cfg.n_kv_heads, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # pages [Hkv, P, ps, D]: scatter the new token's KV per batch row
        knew = k[:, 0].transpose(1, 0, 2)  # [Hkv, B, D]
        vnew = v[:, 0].transpose(1, 0, 2)
        k_pages_l = k_pages_l.at[:, page_ix, offset].set(
            knew.astype(k_pages_l.dtype))
        v_pages_l = v_pages_l.at[:, page_ix, offset].set(
            vnew.astype(v_pages_l.dtype))
        attn = paged_attention(
            q, k_pages_l, v_pages_l, page_table, lengths + 1
        )
        x = x + attn.reshape(B, 1, cfg.n_heads * hd) @ lp["wo"]
        h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.n_experts > 0:
            from .moe import moe_ffn

            y, _ = moe_ffn(
                h.reshape(B, cfg.dim), lp["router"], lp["we1"],
                lp["we3"], lp["we2"], cfg.n_experts_per_tok,
                cfg.capacity_factor,
            )
            x = x + y.reshape(B, 1, cfg.dim)
        else:
            gate = jax.nn.silu(
                (h @ lp["w1"]).astype(jnp.float32)
            ).astype(x.dtype)
            x = x + (gate * (h @ lp["w3"])) @ lp["w2"]
        return x, (k_pages_l, v_pages_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], pages["k"], pages["v"])
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (
        params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    )
    logits = (x[:, 0].astype(jnp.float32) @ head.astype(jnp.float32))
    return logits, {"k": new_k, "v": new_v}


def write_prompt_to_pages(
    pages: Dict[str, jax.Array],
    prefill_cache: Dict[str, jax.Array],  # [L, 1, S_bucket, Hkv, D]
    page_rows: jax.Array,  # [S_bucket // page_size] physical pages
) -> Dict[str, jax.Array]:
    """Scatter a dense bucketed-prefill KV row into this sequence's
    pages (rows past the true prompt length are garbage but masked by
    `lengths` at attention time)."""
    L, _, S, Hkv, D = prefill_cache["k"].shape
    ps = pages["k"].shape[3]
    nb = S // ps
    # [L, S, Hkv, D] -> [L, Hkv, nb, ps, D] (head-major page layout)
    k_rows = prefill_cache["k"][:, 0].reshape(
        L, nb, ps, Hkv, D).transpose(0, 3, 1, 2, 4)
    v_rows = prefill_cache["v"][:, 0].reshape(
        L, nb, ps, Hkv, D).transpose(0, 3, 1, 2, 4)
    return {
        "k": pages["k"].at[:, :, page_rows].set(k_rows),
        "v": pages["v"].at[:, :, page_rows].set(v_rows),
    }


def write_prompts_to_pages(
    pages: Dict[str, jax.Array],
    prefill_cache: Dict[str, jax.Array],  # [L, B, S_bucket, Hkv, D]
    page_rows: jax.Array,  # [B, S_bucket // page_size] physical pages
) -> Dict[str, jax.Array]:
    """Batched write_prompt_to_pages: one scatter covers a whole
    same-bucket prefill group."""
    L, B, S, Hkv, D = prefill_cache["k"].shape
    ps = pages["k"].shape[3]
    nb = S // ps
    k_rows = prefill_cache["k"].reshape(
        L, B * nb, ps, Hkv, D).transpose(0, 3, 1, 2, 4)
    v_rows = prefill_cache["v"].reshape(
        L, B * nb, ps, Hkv, D).transpose(0, 3, 1, 2, 4)
    flat = page_rows.reshape(-1)  # [B*nb]
    return {
        "k": pages["k"].at[:, :, flat].set(k_rows),
        "v": pages["v"].at[:, :, flat].set(v_rows),
    }


def loss_fn(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S+1] (inputs + shifted targets)
    mesh=None,
) -> jax.Array:
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    B, S = inputs.shape
    chunk = int(getattr(cfg, "ce_chunk", 0) or 0)
    if chunk > 0 and S % chunk:
        # a silent dense fallback would quietly forfeit the ~1 GiB
        # peak-HBM saving the flag promises (and OOM configs sized for
        # it) — surface the misconfiguration instead
        raise ValueError(
            f"ce_chunk={chunk} must divide the training sequence "
            f"length S={S} (tokens are [B, S+1])")
    if chunk <= 0 or S == chunk:
        logits, aux = forward(cfg, params, inputs, mesh=mesh,
                              return_aux=True)
        # logsumexp form: no [B, S, vocab] log-softmax tensor
        # materialized (the reduction fuses with the logits matmul's
        # epilogue)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0]
        loss = jnp.mean(lse - tgt)
    else:
        # CHUNKED, REMATERIALIZED cross-entropy: the [B, S, vocab] f32
        # logits tensor (~1 GiB at the flagship size) never fully
        # exists — per-chunk logits are computed, reduced to lse/target
        # scores, and recomputed in the backward pass (jax.checkpoint),
        # cutting both peak HBM and logits write-back traffic. This is
        # what frees enough memory to raise the flagship batch size.
        x, aux = forward(cfg, params, inputs, mesh=mesh,
                         return_hidden=True)
        head = (
            params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"]
        ).astype(cfg.dtype)
        nC = S // chunk
        xs = x.reshape(B, nC, chunk, -1).transpose(1, 0, 2, 3)
        ts = targets.reshape(B, nC, chunk).transpose(1, 0, 2)

        @jax.checkpoint
        def chunk_nll(x_c, t_c):
            logits = jax.lax.dot_general(
                x_c.astype(cfg.dtype), head,
                (((x_c.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, t_c[..., None], axis=-1)[..., 0]
            return jnp.sum(lse - tgt)

        def body(acc, xt):
            x_c, t_c = xt
            return acc + chunk_nll(x_c, t_c), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (xs, ts))
        loss = total / (B * S)
    if cfg.n_experts > 0:
        loss = loss + cfg.router_aux_coef * aux / cfg.n_layers
    return loss
