"""Model zoo: TPU-first reference models driven by the parallel layer.

The reference framework ships no model code in core (models live in vLLM /
torch via ray.llm + ray.train delegation); here models are first-class so
Train/Serve/bench have a flagship to run. All models are functional jax:
param pytrees + logical-axis trees consumed by parallel.sharding rules.
"""
from .llama import (  # noqa: F401
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)
from .training import make_train_step, TrainState  # noqa: F401
