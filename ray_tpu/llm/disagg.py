"""Prefill/decode disaggregation: separate replica pools for the two
phases of LLM inference.

Reference: python/ray/llm/_internal/serve/deployments/prefill_decode_disagg/
(prefill replicas compute the prompt KV and hand it to decode replicas
over NIXL/NCCL). TPU-native transport: the prefill actor returns its KV
block with ``tensor_transport="device"`` (experimental/device_objects),
so the pytree stays in the prefill worker's device memory and moves
point-to-point to the decode worker — the driver only routes the marker.

Why disaggregate: prefill is compute-bound (long matmuls over the whole
prompt) while decode is memory-bandwidth-bound (one token per step);
mixing them in one continuous batch makes prompt arrivals stall decode
latency. Separate pools let each scale and batch independently.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

import ray_tpu as ray

from .engine import EngineConfig, GenerationResult, SamplingParams


class PrefillReplica:
    """Computes prompt KV + the first token; KV stays on device."""

    def __init__(self, model_config, engine_config=None, seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ..models.llama import forward_cached, init_cache, init_params

        self.cfg = model_config
        self.ecfg = engine_config or EngineConfig()
        self.params = init_params(model_config, jax.random.PRNGKey(seed))
        self._jnp = jnp
        cfg = model_config

        def prefill(params, cache1, tokens, true_len):
            zero = jnp.zeros((1,), dtype=jnp.int32)
            logits, cache1 = forward_cached(cfg, params, tokens, cache1,
                                            zero)
            return logits[0, true_len - 1, :], cache1

        self._prefill = jax.jit(prefill, donate_argnums=(1,))
        self._init_cache = init_cache

    def _bucket(self, n: int) -> int:
        # must agree with the decode engine's bucket choice (paged
        # engines filter to page-aligned buckets)
        for b in self.ecfg.effective_prefill_buckets():
            if n <= b and b <= self.ecfg.max_seq_len:
                return b
        return self.ecfg.max_seq_len

    @ray.method(tensor_transport="device")
    def prefill(self, prompt_tokens: List[int]) -> Dict[str, Any]:
        """Returns {"kv": {k, v: [L,1,bucket,Hkv,D]}, "last_logits",
        "prompt_len"} — the kv arrays never leave device memory on the
        normal path. The decode side samples the first token so
        SamplingParams apply uniformly to every generated token."""
        import numpy as np

        n = len(prompt_tokens)
        bucket = self._bucket(n)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, :n] = prompt_tokens
        cache1 = self._init_cache(self.cfg, 1, self.ecfg.max_seq_len)
        last_logits, cache1 = self._prefill(
            self.params, cache1, self._jnp.asarray(tokens), np.int32(n)
        )
        return {
            "kv": {
                "k": cache1["k"][:, :, :bucket],
                "v": cache1["v"][:, :, :bucket],
            },
            "last_logits": last_logits,
            "prompt_len": n,
        }


class DecodeReplica:
    """Continuous-batching decode pool member; admits prefilled KV."""

    def __init__(self, model_config, engine_config=None, seed: int = 0):
        from .engine import LLMEngine

        self.engine = LLMEngine(model_config,
                                engine_config=engine_config, seed=seed)

    def decode(self, prefilled: Dict[str, Any], prompt: List[int],
               params: Optional[SamplingParams] = None
               ) -> GenerationResult:
        import numpy as np

        params = params or SamplingParams()
        first = self.engine._sample(
            np.asarray(prefilled["last_logits"]), params
        )
        req = self.engine.generate_prefilled_async(
            prompt, prefilled["kv"], int(first), params
        )
        if not req.event.wait(300.0):
            raise TimeoutError("disaggregated decode timed out")
        return req.result

    def stats(self):
        return self.engine.stats()


class DisaggregatedLLM:
    """Driver-side router over prefill + decode pools (reference:
    prefill_decode_disagg deployment composition)."""

    def __init__(
        self,
        model_config,
        engine_config: Optional[EngineConfig] = None,
        num_prefill: int = 1,
        num_decode: int = 1,
        seed: int = 0,
        resources_per_replica: Optional[Dict[str, float]] = None,
    ):
        res = resources_per_replica or {"CPU": 1}
        opts = {"num_cpus": res.get("CPU", 1)}
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        batch = (engine_config.max_batch_size if engine_config
                 else EngineConfig.max_batch_size)
        P = ray.remote(PrefillReplica)
        D = ray.remote(DecodeReplica)
        self.prefillers = [
            P.options(**opts).remote(model_config, engine_config, seed)
            for _ in range(num_prefill)
        ]
        # decode() blocks until its request finishes, so the actor must
        # dispatch as many concurrent calls as the engine has slots —
        # otherwise continuous batching degenerates to one-at-a-time
        self.decoders = [
            D.options(max_concurrency=batch, **opts).remote(
                model_config, engine_config, seed)
            for _ in range(num_decode)
        ]
        self._p_rr = itertools.cycle(range(num_prefill))
        self._d_rr = itertools.cycle(range(num_decode))

    def generate_async(self, prompt_tokens: List[int],
                       params: Optional[SamplingParams] = None):
        p = self.prefillers[next(self._p_rr)]
        d = self.decoders[next(self._d_rr)]
        # the prefilled KV ref flows prefill-worker -> decode-worker
        # directly; the driver never materializes it
        kv_ref = p.prefill.remote(prompt_tokens)
        return d.decode.remote(kv_ref, prompt_tokens, params)

    def generate(self, prompt_tokens: List[int],
                 params: Optional[SamplingParams] = None,
                 timeout: float = 300.0) -> GenerationResult:
        return ray.get(self.generate_async(prompt_tokens, params),
                       timeout=timeout)

    def shutdown(self):
        for a in self.prefillers + self.decoders:
            try:
                ray.kill(a)
            except Exception:
                pass
