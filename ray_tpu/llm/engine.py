"""Continuous-batching LLM engine.

Reference shape: vLLM's engine as wrapped by ray.llm
(vllm_engine.py) — here rebuilt TPU-first:
  - fixed slot-array KV cache [L, B, S, Hkv, D]: static shapes so the
    decode step compiles ONCE and streams batches (the compiled-graph
    lesson: keep one XLA program alive, SURVEY §2.3 aDAG row);
  - prefill compiled per power-of-two prompt bucket, single-slot, row
    scattered into the shared cache;
  - the scheduler admits waiting requests into free slots each iteration,
    decodes all active slots in ONE batched step, retires finished ones
    (continuous batching, per-iteration scheduling).
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0
    stop_token_ids: tuple = ()
    seed: Optional[int] = None


@dataclass
class EngineConfig:
    max_batch_size: int = 8
    max_seq_len: int = 1024
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    # KV layout: "slab" = fixed [B, S] slot cache; "paged" = paged KV
    # (ops/paged_attention.py) — memory scales with actual sequence
    # lengths, admission reserves only the pages a request can use.
    kv_layout: str = "slab"
    page_size: int = 32
    num_pages: int = 0  # 0 = max_batch_size * max_seq_len / page_size
    # decode steps per scheduler tick with ON-DEVICE sampling: the token
    # feeds back through a lax.scan without a host round-trip, so per
    # tick only the token ids transfer (vLLM's multi-step scheduling).
    # Tokens generated past a request's stop are discarded host-side;
    # requests needing host sampling (top_k, per-request seed) fall
    # back to single-step ticks. 1 disables.
    decode_chunk: int = 8
    # max prefills fused into one dispatch (power-of-two groups).
    # 1 = one dispatch per admission (default: measured faster when
    # requests trickle in — larger groups delay decode ticks between
    # chunks); raise it for bursty admission patterns on hardware
    # where prefill compute, not dispatch latency, dominates.
    prefill_batch: int = 1
    # compile the batched-prefill shapes (sizes up to prefill_batch per
    # bucket) at engine start instead of on first traffic — serving
    # deployments should pay compiles at boot, not as p95 TTFT spikes
    precompile_prefill: bool = False

    def effective_prefill_buckets(self) -> tuple:
        """Paged layouts admit only page-aligned buckets; prefill
        replicas must agree with decode engines on this."""
        if self.kv_layout != "paged":
            return self.prefill_buckets
        return tuple(
            b for b in self.prefill_buckets if b % self.page_size == 0
        ) or (self.max_seq_len,)


@dataclass
class GenerationResult:
    request_id: int
    prompt_tokens: List[int]
    token_ids: List[int]
    finish_reason: str
    ttft_s: float = 0.0
    latency_s: float = 0.0


class _Request:
    __slots__ = ("rid", "prompt", "params", "generated", "event", "result",
                 "submit_time", "first_token_time", "prefilled", "done_cb",
                 "token_cb", "cancelled")

    def __init__(self, rid, prompt, params, prefilled=None, done_cb=None,
                 token_cb=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.params = params
        self.generated: List[int] = []
        self.event = threading.Event()
        self.result: Optional[GenerationResult] = None
        self.submit_time = time.time()
        self.first_token_time: Optional[float] = None
        # (kv {k,v: [L,1,bucket,Hkv,D]}, first_token) from a prefill
        # replica — decode-side admission skips the prefill compute
        # (prefill/decode disaggregation, llm/disagg.py)
        self.prefilled = prefilled
        # completion hook for asyncio-native callers (agenerate): fires
        # on the scheduler thread after `result` is set — no thread
        # blocked in event.wait() per in-flight request
        self.done_cb = done_cb
        # per-token hook for streaming callers (astream): fires on the
        # scheduler thread as each token folds into host state
        self.token_cb = token_cb
        # consumer abandoned the request (client disconnect): the
        # scheduler frees the slot at the next tick instead of decoding
        # the remaining budget for nobody
        self.cancelled = False

    def emit(self, tok: int):
        """Append a decoded token and notify a streaming consumer."""
        self.generated.append(tok)
        if self.token_cb is not None:
            try:
                self.token_cb(self, tok)
            except Exception:  # noqa: BLE001 — never kill the scheduler
                pass

    def finish(self):
        self.event.set()
        if self.done_cb is not None:
            try:
                self.done_cb(self)
            except Exception:  # noqa: BLE001 — never kill the scheduler
                pass


class LLMEngine:
    def __init__(
        self,
        model_config,
        params: Optional[Any] = None,
        engine_config: Optional[EngineConfig] = None,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        from ..models.llama import forward_cached, init_cache, init_params

        self._jax = jax
        self._jnp = jnp
        self.cfg = model_config
        self.ecfg = engine_config or EngineConfig()
        if self.ecfg.max_seq_len > model_config.max_seq_len:
            self.ecfg.max_seq_len = model_config.max_seq_len
        self.params = (
            params
            if params is not None
            else init_params(model_config, jax.random.PRNGKey(seed))
        )
        B, S = self.ecfg.max_batch_size, self.ecfg.max_seq_len
        self.lengths = np.zeros(B, dtype=np.int32)
        self.slots: List[Optional[_Request]] = [None] * B
        self._rng = np.random.default_rng(seed)

        cfg = model_config
        self.paged = self.ecfg.kv_layout == "paged"
        if self.paged:
            from ..models.llama import (
                forward_paged_decode,
                init_paged_cache,
                write_prompt_to_pages,
            )

            ps = self.ecfg.page_size
            if S % ps:
                raise ValueError(f"max_seq_len {S} not a multiple of "
                                 f"page_size {ps}")
            self.ecfg.prefill_buckets = self.ecfg.effective_prefill_buckets()
            # page 0 is sacrificial scratch: inactive slots' page-table
            # rows are zero, so their masked-out decode writes land there
            # instead of corrupting a live page
            self.num_pages = self.ecfg.num_pages or (B * S // ps + 1)
            self.pages = init_paged_cache(cfg, self.num_pages, ps)
            self.free_pages: List[int] = list(range(1, self.num_pages))
            self.page_tables = np.zeros((B, S // ps), dtype=np.int32)
            self._slot_pages: List[List[int]] = [[] for _ in range(B)]

            def paged_step(params, pages, tokens, page_tables, lengths):
                logits, pages = forward_paged_decode(
                    cfg, params, tokens, pages, page_tables, lengths
                )
                return logits, pages

            self._decode_paged = jax.jit(paged_step, donate_argnums=(1,))
            self._write_pages = jax.jit(write_prompt_to_pages,
                                        donate_argnums=(0,))
        else:
            self.cache = init_cache(model_config, B, S)

        # compile once: batched single-token decode (slab layout)
        def decode_step(params, cache, tokens, lengths):
            logits, cache = forward_cached(cfg, params, tokens, cache,
                                           lengths)
            return logits[:, -1, :], cache

        if not self.paged:
            self._decode = jax.jit(decode_step, donate_argnums=(1,))

        # multi-step decode: `chunk` tokens per dispatch, sampling
        # (greedy / temperature) on device inside the scan
        chunk = max(1, self.ecfg.decode_chunk)

        def _sample_on_device(logits, temps, key):
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            sampled = jax.random.categorical(key, scaled)
            return jnp.where(temps <= 0.0, greedy,
                             sampled).astype(jnp.int32)

        # The multi-step fns RETURN their token/length feedback so the
        # host can chain ticks device-to-device: on a tunneled chip the
        # d2h readback dominates the tick (~24 ms measured vs ~0.1 ms
        # dispatch/upload), so the loop pipelines — dispatch tick N,
        # async-copy its tokens, and only then process tick N-1's.
        # each tick returns ONE packed int32 readback array
        # [chunk*B + B]: the chunk's tokens plus the device-resident
        # first-token buffer (fresh admissions' first samples). On a
        # tunneled chip every d2h transfer is a ~25 ms round trip
        # regardless of size — packing makes a tick cost exactly one.
        # the sampling key derives from the tick counter INSIDE the jit
        # (fold_in of a scalar arg): passing a host int costs nothing,
        # while building the key host-side is two extra device ops per
        # tick on a dispatch-latency-bound tunneled backend
        _base_seed = seed ^ 0x5EED

        if chunk > 1 and not self.paged:
            def decode_multi(params, cache, tokens, lengths, active,
                             temps, counter, firsts):
                key = jax.random.fold_in(
                    jax.random.PRNGKey(_base_seed), counter)

                def step(carry, k):
                    cache, toks, lens = carry
                    logits, cache = forward_cached(
                        cfg, params, toks, cache, lens)
                    tok = _sample_on_device(logits[:, -1, :], temps, k)
                    lens = lens + active
                    return (cache, tok[:, None], lens), tok

                keys = jax.random.split(key, chunk)
                (cache, last, lens), toks = jax.lax.scan(
                    step, (cache, tokens, lengths), keys)
                packed = jnp.concatenate([toks.reshape(-1), firsts])
                return packed, last, lens, cache

            self._decode_multi = jax.jit(decode_multi,
                                         donate_argnums=(1,))
        if chunk > 1 and self.paged:
            from ..models.llama import forward_paged_decode as _fpd

            def decode_multi_paged(params, pages, tokens, page_tables,
                                   lengths, active, temps, counter,
                                   firsts):
                key = jax.random.fold_in(
                    jax.random.PRNGKey(_base_seed), counter)

                def step(carry, k):
                    pages, toks, lens = carry
                    logits, pages = _fpd(
                        cfg, params, toks, pages, page_tables, lens)
                    tok = _sample_on_device(logits, temps, k)
                    lens = lens + active
                    return (pages, tok[:, None], lens), tok

                keys = jax.random.split(key, chunk)
                (pages, last, lens), toks = jax.lax.scan(
                    step, (pages, tokens, lengths), keys)
                packed = jnp.concatenate([toks.reshape(-1), firsts])
                return packed, last, lens, pages

            self._decode_multi_paged = jax.jit(decode_multi_paged,
                                               donate_argnums=(1,))
        # device buffer of fresh admissions' first tokens, scattered at
        # admission and read back inside the next tick's packed array
        self._firsts_dev = jnp.zeros((B,), jnp.int32)
        self._scatter_first = jax.jit(
            lambda buf, i, tok: buf.at[i].set(tok))
        # d2h transfers run on this single reader thread: np.asarray
        # blocks for a full tunnel round trip on this backend (async
        # copies are not honored), so the scheduler thread hands the
        # packed array off and keeps admitting/dispatching while the
        # transfer is in flight
        from concurrent.futures import ThreadPoolExecutor as _TPE

        self._reader = _TPE(max_workers=1, thread_name_prefix="d2h")
        # device copies of the slot-shaped tick inputs (page tables,
        # active mask, temperatures): re-uploaded only when slot state
        # changes — steady-state decode ticks cost ONE dispatch
        self._tick_inputs_dev = None
        self._tick_inputs_dirty = True
        # device-resident (last_tokens, lengths) chained between multi-
        # step ticks; None = host state changed, re-upload next tick
        self._dev_state = None
        # in-flight (tokens_device, active, chunk) from the last
        # dispatched tick, consumed after the NEXT dispatch
        self._pending_tick = None
        # admissions whose first token was sampled ON DEVICE and not yet
        # copied to the host: list of (slot, req, token_dev). The copy
        # merges into the next tick readback — an admission costs no d2h
        # round trip of its own.
        self._pending_first: list = []
        # (slot, token_dev, length) updates to fold into the device
        # chain right before the next dispatch
        self._chain_fixups: list = []
        # grouped admission helpers: ONE dispatch samples a whole prefill
        # group's first tokens and scatters them into the device
        # first-token buffer; one more folds the group into the decode
        # feedback chain. Per-admission eager ops (logits[j] slice,
        # fold_in, scalar sample, scalar scatter) each cost a dispatch
        # round trip — at high admission rates they starve the loop.
        def _sample_firsts_group(logits, temps, key, idx, firsts):
            toks = _sample_on_device(logits, temps, key)  # [G]
            return firsts.at[idx].set(toks), toks

        self._sample_first_group = jax.jit(_sample_firsts_group)
        # works for scalar and grouped (array-index) splices alike
        self._admit_scatter_group = jax.jit(
            lambda toks, lens, idx, tok, ln: (
                toks.at[idx, 0].set(tok), lens.at[idx].set(ln)))
        self._sample_base_key = jax.random.PRNGKey(seed ^ 0x5EED)
        self._tick_counter = 0
        # occupancy accounting: mean active slots per decode tick tells
        # whether a throughput gap is engine-side (ticks slow) or
        # admission-side (slots starved) — exposed in stats()
        self._occ_ticks = 0
        self._occ_active = 0

        # prefill per bucket, single slot (both layouts)
        def prefill(params, cache1, tokens, true_len):
            zero = jnp.zeros((1,), dtype=jnp.int32)
            logits, cache1 = forward_cached(cfg, params, tokens, cache1,
                                            zero)
            last = logits[0, true_len - 1, :]
            return last, cache1

        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        # batched prefill: one dispatch per same-bucket admission group
        def prefill_batch(params, cacheB, tokens, true_lens):
            zeros = jnp.zeros((tokens.shape[0],), dtype=jnp.int32)
            logits, cacheB = forward_cached(cfg, params, tokens, cacheB,
                                            zeros)
            last = logits[jnp.arange(tokens.shape[0]), true_lens - 1, :]
            return last, cacheB

        self._prefill_batch = jax.jit(prefill_batch, donate_argnums=(1,))
        if self.paged:
            from ..models.llama import write_prompts_to_pages

            self._write_pages_batch = jax.jit(
                write_prompts_to_pages, donate_argnums=(0,))
        else:
            def scatter_slots(cache, cacheB, idx):
                return {
                    "k": cache["k"].at[:, idx].set(cacheB["k"]),
                    "v": cache["v"].at[:, idx].set(cacheB["v"]),
                }

            self._scatter_slots = jax.jit(scatter_slots,
                                          donate_argnums=(0,))

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        # head-of-line request whose page reservation is pending: retried
        # before the queue so big requests aren't starved by later small
        # ones grabbing freed pages
        self._parked: Optional[_Request] = None
        self._next_rid = 0
        self._rid_lock = threading.Lock()
        self._stop = threading.Event()
        # scheduler-loop exception count (VERDICT r3 Weak #7): exposed in
        # stats(), exported as a metric, asserted zero by tests/benches
        self.loop_errors = 0
        self._last_loop_error: Optional[str] = None
        from .._private.metrics import get_registry

        self._loop_error_metric = get_registry().counter(
            "serve_engine_loop_errors",
            "LLM engine scheduler loop exceptions",
        )
        self._precompiled = threading.Event()
        if self.ecfg.precompile_prefill:
            # background: blocking the constructor would starve the
            # replica's health checks and get it killed mid-boot.
            # Callers gate traffic on is_ready() (LLMServer.ready) so
            # steady-state serving never races compiles for the chip.
            threading.Thread(target=self._precompile_prefill_shapes,
                             daemon=True).start()
        else:
            self._precompiled.set()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def is_ready(self) -> bool:
        return self._precompiled.is_set()

    def wait_ready(self, timeout: float = 600.0) -> bool:
        return self._precompiled.wait(timeout)

    def _precompile_prefill_shapes(self):
        """Compile the prefill FORWARD shapes the engine will actually
        dispatch: the singleton path per bucket, plus each power-of-two
        group size up to prefill_batch. Throwaway caches only — live
        cache/pages state is never donated from this thread (the
        scheduler loop runs concurrently). The small KV-scatter
        compiles still happen on first use; forwards dominate."""
        import jax.numpy as jnp

        from ..models.llama import init_cache

        sizes = [1]
        b = 2
        cap = 1 << (max(1, self.ecfg.prefill_batch).bit_length() - 1)
        while b <= min(self.ecfg.max_batch_size, cap):
            sizes.append(b)
            b *= 2
        for bucket in self.ecfg.prefill_buckets:
            if bucket > self.ecfg.max_seq_len:
                continue
            # singleton groups run the single-prefill jit
            cache1 = init_cache(self.cfg, 1, self.ecfg.max_seq_len)
            self._prefill(
                self.params, cache1,
                jnp.zeros((1, bucket), jnp.int32), np.int32(1),
            )
            for bp in sizes[1:]:
                cacheB = init_cache(self.cfg, bp, self.ecfg.max_seq_len)
                self._prefill_batch(
                    self.params, cacheB,
                    jnp.zeros((bp, bucket), jnp.int32),
                    jnp.ones((bp,), jnp.int32),
                )
        self._precompiled.set()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def generate_async(self, prompt_tokens: List[int],
                       params: Optional[SamplingParams] = None) -> _Request:
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams())
        if len(req.prompt) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}"
            )
        self._queue.put(req)
        return req

    def generate_prefilled_async(
        self,
        prompt_tokens: List[int],
        kv: Dict[str, Any],  # {k, v: [L, 1, bucket, Hkv, D]}
        first_token: int,
        params: Optional[SamplingParams] = None,
    ) -> _Request:
        """Admit a request whose prefill ran on another replica
        (prefill/decode disaggregation)."""
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams(),
                       prefilled=(kv, first_token))
        self._queue.put(req)
        return req

    async def agenerate(self, prompt_tokens: List[int],
                        params: Optional[SamplingParams] = None,
                        timeout: float = 300.0) -> GenerationResult:
        """Asyncio-native generate: completion wakes the awaiting loop
        via call_soon_threadsafe — no thread parked in event.wait() per
        in-flight request. On 1-vCPU hosts the asyncio default executor
        is ~5 threads, so thread-per-request serving silently caps
        engine concurrency below the batch size; this path multiplexes
        any number of requests on the replica's loop."""
        import asyncio

        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()

        def _done(req):
            def _set():
                if not fut.done():
                    fut.set_result(req.result)

            loop.call_soon_threadsafe(_set)

        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams(),
                       done_cb=_done)
        if len(req.prompt) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}"
            )
        self._queue.put(req)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"generation {req.rid} timed out")

    async def astream(self, prompt_tokens: List[int],
                      params: Optional[SamplingParams] = None,
                      timeout: float = 300.0):
        """Async generator over a request's tokens AS DECODED: yields
        {"token": id} per token, then {"done": GenerationResult}. The
        scheduler thread enqueues through call_soon_threadsafe; the
        consumer observes TTFT = first yield, not time-to-last-token
        (reference: vLLM AsyncLLMEngine.generate's async iterator)."""
        import asyncio

        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def _tok(req, tok):
            loop.call_soon_threadsafe(q.put_nowait, ("tok", tok))

        def _done(req):
            loop.call_soon_threadsafe(q.put_nowait, ("done", req.result))

        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams(),
                       done_cb=_done, token_cb=_tok)
        if len(req.prompt) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}"
            )
        self._queue.put(req)
        deadline = time.time() + timeout
        try:
            while True:
                rem = deadline - time.time()
                if rem <= 0:
                    raise TimeoutError(f"generation {req.rid} timed out")
                kind, val = await asyncio.wait_for(q.get(), rem)
                if kind == "tok":
                    yield {"token": int(val), "rid": req.rid}
                else:
                    yield {"done": val}
                    return
        finally:
            # consumer stopped early (client disconnect closes the
            # generator, or the wait timed out): tell the scheduler to
            # free the slot instead of decoding the rest for nobody
            if req.result is None:
                req.cancelled = True

    def generate(self, prompt_tokens: List[int],
                 params: Optional[SamplingParams] = None,
                 timeout: float = 300.0) -> GenerationResult:
        req = self.generate_async(prompt_tokens, params)
        if not req.event.wait(timeout):
            raise TimeoutError(f"generation {req.rid} timed out")
        return req.result

    def generate_batch(self, prompts: List[List[int]],
                       params: Optional[SamplingParams] = None,
                       timeout: float = 600.0) -> List[GenerationResult]:
        reqs = [self.generate_async(p, params) for p in prompts]
        out = []
        deadline = time.time() + timeout
        for r in reqs:
            if not r.event.wait(max(0.0, deadline - time.time())):
                raise TimeoutError("batch generation timed out")
            out.append(r.result)
        return out

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._reader.shutdown(wait=False)

    def stats(self) -> Dict[str, Any]:
        out = {
            "active": sum(s is not None for s in self.slots),
            "waiting": self._queue.qsize(),
            "max_batch": self.ecfg.max_batch_size,
            "kv_layout": self.ecfg.kv_layout,
            "backend": self._jax.default_backend(),
            "loop_errors": self.loop_errors,
            "decode_ticks": self._occ_ticks,
            "mean_occupancy": (
                round(self._occ_active / self._occ_ticks, 2)
                if self._occ_ticks else 0.0
            ),
        }
        if self.paged:
            out["free_pages"] = len(self.free_pages)
            out["total_pages"] = self.num_pages - 1  # minus scratch
        return out

    # ------------------------------------------------------------------
    # scheduler loop
    # ------------------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.ecfg.prefill_buckets:
            if n <= b and b <= self.ecfg.max_seq_len:
                return b
        return self.ecfg.max_seq_len

    def _loop(self):
        jnp = self._jnp
        while not self._stop.is_set():
            try:
                self._loop_once(jnp)
            except Exception:  # noqa: BLE001 — scheduler must survive
                import traceback

                err = traceback.format_exc()
                # count every loop exception: a bug here (e.g. an idle-
                # tick crash-loop) is otherwise invisible — no request
                # fails, the handler just silently rebuilds the cache.
                # Benches/tests assert this stays 0.
                self.loop_errors += 1
                self._last_loop_error = err
                self._loop_error_metric.inc()
                if self.loop_errors <= 3 or self.loop_errors % 100 == 0:
                    import logging

                    logging.getLogger(__name__).error(
                        "engine scheduler loop error #%d:\n%s",
                        self.loop_errors, err,
                    )
                for i, req in enumerate(self.slots):
                    if req is not None:
                        self._finish_with_error(i, err)
                # decode/prefill donate the cache buffer (donate_argnums):
                # an exception after donation leaves the cache permanently
                # invalid, which would fail every future request. Rebuild.
                if self.paged:
                    from ..models.llama import init_paged_cache

                    self.pages = init_paged_cache(
                        self.cfg, self.num_pages, self.ecfg.page_size
                    )
                    self.free_pages = list(range(1, self.num_pages))
                    self._slot_pages = [
                        [] for _ in range(self.ecfg.max_batch_size)
                    ]
                    self.page_tables[:] = 0
                else:
                    from ..models.llama import init_cache

                    self.cache = init_cache(
                        self.cfg, self.ecfg.max_batch_size,
                        self.ecfg.max_seq_len,
                    )
                self.lengths[:] = 0
                self.slots = [None] * self.ecfg.max_batch_size
                # the pipelined tick and device feedback chain reference
                # the donated (now rebuilt) buffers — reset both, and
                # drop queued admission fixups/first-tokens: their slots
                # were failed above, and a stale scatter applied to a
                # future occupant of the same slot would corrupt its
                # device length/token chain
                self._pending_tick = None
                self._dev_state = None
                self._chain_fixups.clear()
                self._pending_first.clear()
                self._tick_inputs_dirty = True
                time.sleep(0.05)

    def _finish_with_error(self, i: int, err: str):
        req = self.slots[i]
        req.result = GenerationResult(
            request_id=req.rid,
            prompt_tokens=req.prompt,
            token_ids=list(req.generated),
            finish_reason=f"error: {err.splitlines()[-1][:200]}",
            latency_s=time.time() - req.submit_time,
        )
        self.slots[i] = None
        self.lengths[i] = 0
        self._free_slot_pages(i)
        req.finish()

    def _loop_once(self, jnp):
            self._reap_cancelled()
            admitted = self._admit()
            if self._dev_state is None:
                # broken chain (host-sampled admission, single-step
                # fallback, or error recovery): the host mirrors must
                # fold in EVERY dispatched tick before they are
                # re-uploaded, or the next tick replays the in-flight
                # one (double-appending its tokens)
                self._drain_pending_tick()
            active = [i for i, s in enumerate(self.slots) if s is not None]
            if not active:
                if self._pending_tick is not None:
                    self._drain_pending_tick()
                elif not admitted:
                    time.sleep(0.002)
                return
            last_tokens = np.zeros(
                (self.ecfg.max_batch_size, 1), dtype=np.int32
            )
            for i in active:
                req = self.slots[i]
                last_tokens[i, 0] = (
                    req.generated[-1] if req.generated else req.prompt[-1]
                )
            chunk = max(1, self.ecfg.decode_chunk)
            # with a tick in flight the device lengths run ahead of the
            # host mirror by up to one chunk — keep that margin in bounds
            margin = chunk * (2 if self._pending_tick is not None else 1)
            use_multi = (
                chunk > 1
                and all(
                    self.slots[i].params.top_k in (0, None)
                    and self.slots[i].params.seed is None
                    for i in active
                )
                # overshoot inside the chunk must stay within bounds
                and int(self.lengths[active].max()) + margin
                < self.ecfg.max_seq_len
            )
            if use_multi:
                self._decode_chunk(jnp, active, last_tokens, chunk)
                return
            # single batched decode step for every active slot: host
            # sampling needs host lengths to be exact — drain the
            # pipelined tick and resolve device-held first tokens first
            self._drain_pending_tick()
            self._resolve_pending_first()
            self._dev_state = None
            self._chain_fixups.clear()
            active = [i for i, s in enumerate(self.slots) if s is not None]
            if not active:
                return
            for i in active:
                req = self.slots[i]
                last_tokens[i, 0] = (
                    req.generated[-1] if req.generated else req.prompt[-1]
                )
            self._occ_ticks += 1
            self._occ_active += len(active)
            if self.paged:
                logits, self.pages = self._decode_paged(
                    self.params,
                    self.pages,
                    jnp.asarray(last_tokens),
                    jnp.asarray(self.page_tables),
                    jnp.asarray(self.lengths),
                )
            else:
                logits, self.cache = self._decode(
                    self.params,
                    self.cache,
                    jnp.asarray(last_tokens),
                    jnp.asarray(self.lengths),
                )
            logits_np = np.asarray(logits)
            self.lengths[active] += 1
            now = time.time()
            for i in active:
                req = self.slots[i]
                tok = self._sample(logits_np[i], req.params)
                req.emit(int(tok))
                if req.first_token_time is None:
                    req.first_token_time = now
                self._maybe_finish(i)

    def _decode_chunk(self, jnp, active, last_tokens, chunk):
        """Multi-step decode, PIPELINED: `chunk` tokens per dispatch with
        on-device sampling, token/length feedback chained device-side
        (no per-tick upload), and the token readback of tick N consumed
        only after tick N+1 is dispatched — the ~24 ms tunneled-d2h
        latency overlaps the next tick's compute instead of serializing
        with it. Tokens past a request's stop are discarded (the cache
        positions they wrote are beyond the request's final length and
        are never read; device lengths for continuing slots stay exact
        because only finishing conditions truncate a chunk)."""
        B = self.ecfg.max_batch_size
        self._occ_ticks += 1
        self._occ_active += len(active)
        self._tick_counter += 1
        if self._tick_inputs_dirty or self._tick_inputs_dev is None:
            active_mask = np.zeros(B, dtype=np.int32)
            active_mask[active] = 1
            temps = np.zeros(B, dtype=np.float32)
            for i in active:
                temps[i] = self.slots[i].params.temperature
            self._tick_inputs_dev = (
                jnp.asarray(self.page_tables) if self.paged else None,
                jnp.asarray(active_mask),
                jnp.asarray(temps),
            )
            self._tick_inputs_dirty = False
        pt_dev, mask_dev, temps_dev = self._tick_inputs_dev
        if self._dev_state is not None:
            tokens_in, lengths_in = self._dev_state
        else:
            tokens_in = jnp.asarray(last_tokens)
            lengths_in = jnp.asarray(self.lengths)
        # fold freshly admitted slots into the chain ON DEVICE (their
        # first tokens exist only there; see _pending_first) — one
        # grouped scatter per admission group
        if self._chain_fixups:
            for idx, toks_g, lens_g in self._chain_fixups:
                tokens_in, lengths_in = self._admit_scatter_group(
                    tokens_in, lengths_in, idx, toks_g, lens_g)
            self._chain_fixups.clear()
        counter = np.int32(self._tick_counter)
        if self.paged:
            packed, last, lens, self.pages = self._decode_multi_paged(
                self.params, self.pages, tokens_in,
                pt_dev, lengths_in, mask_dev, temps_dev, counter,
                self._firsts_dev,
            )
        else:
            packed, last, lens, self.cache = self._decode_multi(
                self.params, self.cache, tokens_in,
                lengths_in, mask_dev, temps_dev, counter,
                self._firsts_dev,
            )
        self._dev_state = (last, lens)
        try:
            packed.copy_to_host_async()
        except Exception:
            pass  # backend without async copy: np.asarray blocks later
        # capture request IDENTITY, not just slot index: a slot can be
        # freed and re-admitted between this dispatch and the consume,
        # and the new occupant must not inherit the old one's tokens.
        # Fresh admissions' pending-first entries travel WITH the tick
        # whose packed array holds their tokens.
        pend, self._pending_first = self._pending_first, []
        fut = self._reader.submit(np.asarray, packed)
        prev, self._pending_tick = (
            self._pending_tick,
            (fut, [(i, self.slots[i]) for i in active], chunk, pend))
        if prev is not None:
            self._consume_tick(*prev)

    def _drain_pending_tick(self):
        prev, self._pending_tick = self._pending_tick, None
        if prev is not None:
            self._consume_tick(*prev)
        elif self._pending_first:
            self._resolve_pending_first()

    def _resolve_pending_first(self):
        """Copy device-held first tokens to the host (outside a tick
        readback — used by the single-step fallback and idle drains).
        Entries reference (group_tokens_dev, row); one transfer per
        admission group, cached across entries."""
        pend, self._pending_first = self._pending_first, []
        cache: dict = {}
        for slot, req, (toks_g, g) in pend:
            if self.slots[slot] is not req:
                continue
            arr = cache.get(id(toks_g))
            if arr is None:
                arr = cache[id(toks_g)] = np.asarray(toks_g)
            req.emit(int(arr[g]))
            self._maybe_finish(slot)

    def _consume_tick(self, packed_dev, active, chunk, pend=()):
        """Fold a completed tick's tokens into host state. The packed
        readback [chunk*B + B] holds the tick's tokens plus the
        first-token buffer of admissions that traveled with the tick —
        ONE d2h transfer resolves both (on a tunneled chip every
        transfer is a full round trip, so count matters, not bytes).
        First tokens PRECEDE this tick's tokens for their slots; fold
        order preserves sequence order. Finished slots do NOT break the
        device chain: their rows go inactive, and the garbage their
        stale lengths produce lands on the paged layout's sacrificial
        page 0 / the dead slab rows, both rewritten at the next
        admission."""
        B = self.ecfg.max_batch_size
        merged = (packed_dev.result() if hasattr(packed_dev, "result")
                  else np.asarray(packed_dev))
        toks_np = merged[: chunk * B].reshape(chunk, B)
        firsts_np = merged[chunk * B:]
        for slot, req, _tok_dev in pend:
            if self.slots[slot] is not req:
                continue
            req.emit(int(firsts_np[slot]))
            self._maybe_finish(slot)
        now = time.time()
        for i, req in active:
            if req is None or self.slots[i] is not req:
                continue  # freed (or slot re-admitted) since dispatch
            consumed = 0
            for step in range(chunk):
                req.emit(int(toks_np[step, i]))
                consumed += 1
                if req.first_token_time is None:
                    req.first_token_time = now
                if self._is_finished(req):
                    break
            self.lengths[i] += consumed
            self._maybe_finish(i)

    def _admit(self) -> bool:
        jnp = self._jnp
        admitted = False
        to_prefill: list = []
        for i in range(self.ecfg.max_batch_size):
            if self.slots[i] is not None:
                continue
            if self._parked is not None:
                req, self._parked = self._parked, None
            else:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
            if req.cancelled:
                # consumer gone before admission: never pay its prefill
                req.result = GenerationResult(
                    request_id=req.rid, prompt_tokens=req.prompt,
                    token_ids=[], finish_reason="cancelled",
                )
                req.finish()
                continue
            bucket = self._bucket(len(req.prompt))
            if self.paged and not self._reserve_pages(i, req, bucket):
                ps = self.ecfg.page_size
                horizon = min(
                    len(req.prompt) + req.params.max_tokens + 1,
                    self.ecfg.max_seq_len,
                )
                need = max(bucket // ps, -(-horizon // ps))
                if need > self.num_pages - 1:
                    # can never fit: fail fast instead of spinning
                    req.result = GenerationResult(
                        request_id=req.rid,
                        prompt_tokens=req.prompt,
                        token_ids=[],
                        finish_reason=(
                            f"error: request needs {need} KV pages but "
                            f"the engine has {self.num_pages - 1}"
                        ),
                        latency_s=time.time() - req.submit_time,
                    )
                    req.finish()
                    continue
                # wait head-of-line until pages free up
                self._parked = req
                break
            if req.prefilled is not None:
                # disaggregated admission: KV arrived from a prefill
                # replica (device transport); install it and skip the
                # prefill compute entirely
                kv, first_tok = req.prefilled
                req.prefilled = None  # free the transferred copy
                kvb = kv["k"].shape[2]
                if self.paged:
                    ps = self.ecfg.page_size
                    rows = jnp.asarray(
                        self._slot_pages[i][: kvb // ps],
                        dtype=jnp.int32,
                    )
                    self.pages = self._write_pages(self.pages, kv, rows)
                else:
                    self.cache = {
                        "k": self.cache["k"].at[:, i, :kvb].set(
                            kv["k"][:, 0]),
                        "v": self.cache["v"].at[:, i, :kvb].set(
                            kv["v"][:, 0]),
                    }
                self.lengths[i] = len(req.prompt)
                req.emit(int(first_tok))
                req.first_token_time = req.first_token_time or time.time()
                self.slots[i] = req
                # disagg admissions bypass _finish_admissions: the
                # cached tick inputs must still pick up the new slot
                self._tick_inputs_dirty = True
                admitted = True
                self._maybe_finish(i)
                if self.slots[i] is not None:
                    # splice the transferred first token into the live
                    # decode chain (value is host-known; upload is cheap)
                    self._chain_fixups.append(
                        (i, jnp.asarray(int(first_tok), jnp.int32),
                         len(req.prompt)))
                continue
            to_prefill.append((i, req, bucket))
            self.slots[i] = req  # reserve the slot now
            admitted = True
        if to_prefill:
            self._prefill_groups(to_prefill)
        return admitted

    def _prefill_groups(self, to_prefill):
        """Prefill admitted requests grouped by bucket: ONE forward
        dispatch (and one KV scatter) per group instead of one per
        request (the reference gets this from vLLM's batched prefill;
        on dispatch-latency-bound backends it's the admission
        bottleneck)."""
        jnp = self._jnp
        groups: Dict[int, list] = {}
        for item in to_prefill:
            groups.setdefault(item[2], []).append(item)
        # quantize group sizes to powers of two (7 -> 4+2+1): every
        # distinct (size, bucket) shape is a separate XLA compile, so
        # arbitrary sizes would stall the data plane on fresh compiles
        # mid-traffic
        quantized: list = []
        for bucket, items in groups.items():
            pos = 0
            while pos < len(items):
                take = 1 << ((len(items) - pos).bit_length() - 1)
                # cap is rounded DOWN to a power of two: every shape
                # dispatched here must be in the precompiled set
                cap = 1 << (max(1, self.ecfg.prefill_batch)
                            .bit_length() - 1)
                take = min(take, cap)
                quantized.append((bucket, items[pos:pos + take]))
                pos += take
        for bucket, items in quantized:
            Bp = len(items)
            if Bp == 1:
                # singleton: the original single-prefill path (identical
                # cost profile to pre-batching behavior)
                self._prefill_one(*items[0])
                continue
            tokens = np.zeros((Bp, bucket), dtype=np.int32)
            true_lens = np.zeros((Bp,), dtype=np.int32)
            for j, (_i, req, _b) in enumerate(items):
                tokens[j, : len(req.prompt)] = req.prompt
                true_lens[j] = len(req.prompt)
            from ..models.llama import init_cache

            cacheB = init_cache(self.cfg, Bp, self.ecfg.max_seq_len)
            last_logits, cacheB = self._prefill_batch(
                self.params, cacheB, jnp.asarray(tokens),
                jnp.asarray(true_lens),
            )
            if self.paged:
                ps = self.ecfg.page_size
                nb = bucket // ps
                rows = np.stack([
                    np.asarray(self._slot_pages[i][:nb], dtype=np.int32)
                    for i, _r, _b in items
                ])
                sliced = {
                    "k": cacheB["k"][:, :, :bucket],
                    "v": cacheB["v"][:, :, :bucket],
                }
                self.pages = self._write_pages_batch(
                    self.pages, sliced, jnp.asarray(rows))
            else:
                idx = jnp.asarray([i for i, _r, _b in items],
                                  dtype=jnp.int32)
                self.cache = self._scatter_slots(
                    self.cache, cacheB, idx)
            self._finish_admissions(
                [(i, req) for i, req, _b in items], last_logits)

    def _prefill_one(self, i, req, bucket):
        jnp = self._jnp
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, : len(req.prompt)] = req.prompt
        from ..models.llama import init_cache

        cache1 = init_cache(self.cfg, 1, self.ecfg.max_seq_len)
        last_logits, cache1 = self._prefill(
            self.params, cache1, jnp.asarray(tokens),
            np.int32(len(req.prompt)),
        )
        if self.paged:
            ps = self.ecfg.page_size
            nb = bucket // ps
            rows = jnp.asarray(self._slot_pages[i][:nb], dtype=jnp.int32)
            sliced = {
                "k": cache1["k"][:, :, :bucket],
                "v": cache1["v"][:, :, :bucket],
            }
            self.pages = self._write_pages(self.pages, sliced, rows)
        else:
            self.cache = {
                "k": self.cache["k"].at[:, i].set(cache1["k"][:, 0]),
                "v": self.cache["v"].at[:, i].set(cache1["v"][:, 0]),
            }
        self._finish_admissions([(i, req)], last_logits[None, :])

    def _finish_admissions(self, items, last_logits):
        """Install admitted requests' first tokens. Device-sampleable
        requests (greedy/temperature) sample ON DEVICE in ONE grouped
        dispatch, defer the host copy to the next tick readback, and
        scatter straight into the decode feedback chain — an admission
        group costs zero extra d2h round trips and O(1) dispatches.
        Host-sampled requests (top_k / per-request seed) read the
        logits back and break the chain (rare path)."""
        jax = self._jax
        jnp = self._jnp
        logits_np = None
        now = time.time()
        self._tick_inputs_dirty = True  # new slots: re-upload tick inputs
        dev_rows: list = []  # (row j in last_logits, slot i, req)
        for j, (i, req) in enumerate(items):
            self.lengths[i] = len(req.prompt)
            req.first_token_time = now
            if req.params.top_k in (0, None) and req.params.seed is None:
                dev_rows.append((j, i, req))
            else:
                if logits_np is None:
                    logits_np = np.asarray(last_logits)
                tok = self._sample(logits_np[j], req.params)
                req.emit(int(tok))
                self._dev_state = None  # host mirrors are authoritative
                self._maybe_finish(i)
        if not dev_rows:
            return
        self._tick_counter += 1
        key = jax.random.fold_in(self._sample_base_key,
                                 self._tick_counter)
        rows = np.asarray([j for j, _i, _r in dev_rows], dtype=np.int32)
        idx = np.asarray([i for _j, i, _r in dev_rows], dtype=np.int32)
        temps = np.asarray(
            [r.params.temperature for _j, _i, r in dev_rows], np.float32)
        lens = np.asarray(
            [len(r.prompt) for _j, _i, r in dev_rows], np.int32)
        logits_g = (last_logits if len(dev_rows) == len(items)
                    else last_logits[jnp.asarray(rows)])
        self._firsts_dev, toks_g = self._sample_first_group(
            logits_g, jnp.asarray(temps), key, jnp.asarray(idx),
            self._firsts_dev)
        for g, (_j, i, req) in enumerate(dev_rows):
            self._pending_first.append((i, req, (toks_g, g)))
        # one grouped chain fixup: applied at the next multi-step
        # dispatch (or discarded when the chain breaks)
        self._chain_fixups.append(
            (jnp.asarray(idx), toks_g, jnp.asarray(lens)))

    def _reserve_pages(self, i: int, req: "_Request", bucket: int) -> bool:
        """Allocate exactly the pages this request can ever touch:
        max(prefill bucket, prompt+max_tokens+1) rounded to pages."""
        ps = self.ecfg.page_size
        horizon = min(len(req.prompt) + req.params.max_tokens + 1,
                      self.ecfg.max_seq_len)
        need = max(bucket // ps, -(-horizon // ps))
        if len(self.free_pages) < need:
            return False
        pages = [self.free_pages.pop() for _ in range(need)]
        self._slot_pages[i] = pages
        row = np.zeros(self.page_tables.shape[1], dtype=np.int32)
        row[: len(pages)] = pages
        self.page_tables[i] = row
        return True

    def _free_slot_pages(self, i: int):
        # slot state changed: next tick re-uploads mask/temps/page table
        self._tick_inputs_dirty = True
        if self.paged:
            self.free_pages.extend(self._slot_pages[i])
            self._slot_pages[i] = []
            self.page_tables[i] = 0

    def _sample(self, logits: np.ndarray, params: SamplingParams) -> int:
        if params.temperature <= 0.0:
            return int(np.argmax(logits))
        logits = logits / params.temperature
        if params.top_k and params.top_k > 0:
            kth = np.partition(logits, -params.top_k)[-params.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        logits = logits - logits.max()
        p = np.exp(logits)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _is_finished(self, req: "_Request") -> bool:
        return bool(
            (req.generated
             and req.generated[-1] in req.params.stop_token_ids)
            or len(req.generated) >= req.params.max_tokens
        )

    def _reap_cancelled(self):
        """Free slots whose consumer disconnected (request.cancelled):
        continuing to decode them burns chip time for nobody. Runs at
        tick start so an in-flight tick's tokens for the slot are
        already folded or harmlessly discarded."""
        for i, req in enumerate(self.slots):
            if req is None or not req.cancelled:
                continue
            now = time.time()
            req.result = GenerationResult(
                request_id=req.rid,
                prompt_tokens=req.prompt,
                token_ids=list(req.generated),
                finish_reason="cancelled",
                ttft_s=(req.first_token_time or now) - req.submit_time,
                latency_s=now - req.submit_time,
            )
            self.slots[i] = None
            self.lengths[i] = 0
            self._free_slot_pages(i)
            self._tick_inputs_dirty = True
            req.finish()

    def _maybe_finish(self, i: int):
        req = self.slots[i]
        reason = None
        if self._is_finished(req):
            reason = (
                "stop"
                if req.generated[-1] in req.params.stop_token_ids
                else "length"
            )
        elif self.lengths[i] + 1 >= self.ecfg.max_seq_len:
            reason = "max_seq_len"
        if reason is None:
            return
        now = time.time()
        req.result = GenerationResult(
            request_id=req.rid,
            prompt_tokens=req.prompt,
            token_ids=list(req.generated),
            finish_reason=reason,
            ttft_s=(req.first_token_time or now) - req.submit_time,
            latency_s=now - req.submit_time,
        )
        self.slots[i] = None
        self.lengths[i] = 0
        self._free_slot_pages(i)
        req.finish()
