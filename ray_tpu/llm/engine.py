"""Continuous-batching LLM engine.

Reference shape: vLLM's engine as wrapped by ray.llm
(vllm_engine.py) — here rebuilt TPU-first:
  - fixed slot-array KV cache [L, B, S, Hkv, D]: static shapes so the
    decode step compiles ONCE and streams batches (the compiled-graph
    lesson: keep one XLA program alive, SURVEY §2.3 aDAG row);
  - prefill compiled per power-of-two prompt bucket, single-slot, row
    scattered into the shared cache;
  - the scheduler admits waiting requests into free slots each iteration,
    decodes all active slots in ONE batched step, retires finished ones
    (continuous batching, per-iteration scheduling).
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0
    stop_token_ids: tuple = ()
    seed: Optional[int] = None


@dataclass
class EngineConfig:
    max_batch_size: int = 8
    max_seq_len: int = 1024
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    # KV layout: "slab" = fixed [B, S] slot cache; "paged" = paged KV
    # (ops/paged_attention.py) — memory scales with actual sequence
    # lengths, admission reserves only the pages a request can use.
    kv_layout: str = "slab"
    page_size: int = 32
    num_pages: int = 0  # 0 = max_batch_size * max_seq_len / page_size
    # decode steps per scheduler tick with ON-DEVICE sampling: the token
    # feeds back through a lax.scan without a host round-trip, so per
    # tick only the token ids transfer (vLLM's multi-step scheduling).
    # Tokens generated past a request's stop are discarded host-side;
    # requests needing host sampling (top_k, per-request seed) fall
    # back to single-step ticks. 1 disables.
    decode_chunk: int = 8
    # max prefills fused into one dispatch (power-of-two groups).
    # 1 = one dispatch per admission (default: measured faster when
    # requests trickle in — larger groups delay decode ticks between
    # chunks); raise it for bursty admission patterns on hardware
    # where prefill compute, not dispatch latency, dominates.
    prefill_batch: int = 1
    # compile the batched-prefill shapes (sizes up to prefill_batch per
    # bucket) at engine start instead of on first traffic — serving
    # deployments should pay compiles at boot, not as p95 TTFT spikes
    precompile_prefill: bool = False

    def effective_prefill_buckets(self) -> tuple:
        """Paged layouts admit only page-aligned buckets; prefill
        replicas must agree with decode engines on this."""
        if self.kv_layout != "paged":
            return self.prefill_buckets
        return tuple(
            b for b in self.prefill_buckets if b % self.page_size == 0
        ) or (self.max_seq_len,)


@dataclass
class GenerationResult:
    request_id: int
    prompt_tokens: List[int]
    token_ids: List[int]
    finish_reason: str
    ttft_s: float = 0.0
    latency_s: float = 0.0


class _Request:
    __slots__ = ("rid", "prompt", "params", "generated", "event", "result",
                 "submit_time", "first_token_time", "prefilled")

    def __init__(self, rid, prompt, params, prefilled=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.params = params
        self.generated: List[int] = []
        self.event = threading.Event()
        self.result: Optional[GenerationResult] = None
        self.submit_time = time.time()
        self.first_token_time: Optional[float] = None
        # (kv {k,v: [L,1,bucket,Hkv,D]}, first_token) from a prefill
        # replica — decode-side admission skips the prefill compute
        # (prefill/decode disaggregation, llm/disagg.py)
        self.prefilled = prefilled


class LLMEngine:
    def __init__(
        self,
        model_config,
        params: Optional[Any] = None,
        engine_config: Optional[EngineConfig] = None,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        from ..models.llama import forward_cached, init_cache, init_params

        self._jax = jax
        self._jnp = jnp
        self.cfg = model_config
        self.ecfg = engine_config or EngineConfig()
        if self.ecfg.max_seq_len > model_config.max_seq_len:
            self.ecfg.max_seq_len = model_config.max_seq_len
        self.params = (
            params
            if params is not None
            else init_params(model_config, jax.random.PRNGKey(seed))
        )
        B, S = self.ecfg.max_batch_size, self.ecfg.max_seq_len
        self.lengths = np.zeros(B, dtype=np.int32)
        self.slots: List[Optional[_Request]] = [None] * B
        self._rng = np.random.default_rng(seed)

        cfg = model_config
        self.paged = self.ecfg.kv_layout == "paged"
        if self.paged:
            from ..models.llama import (
                forward_paged_decode,
                init_paged_cache,
                write_prompt_to_pages,
            )

            ps = self.ecfg.page_size
            if S % ps:
                raise ValueError(f"max_seq_len {S} not a multiple of "
                                 f"page_size {ps}")
            self.ecfg.prefill_buckets = self.ecfg.effective_prefill_buckets()
            # page 0 is sacrificial scratch: inactive slots' page-table
            # rows are zero, so their masked-out decode writes land there
            # instead of corrupting a live page
            self.num_pages = self.ecfg.num_pages or (B * S // ps + 1)
            self.pages = init_paged_cache(cfg, self.num_pages, ps)
            self.free_pages: List[int] = list(range(1, self.num_pages))
            self.page_tables = np.zeros((B, S // ps), dtype=np.int32)
            self._slot_pages: List[List[int]] = [[] for _ in range(B)]

            def paged_step(params, pages, tokens, page_tables, lengths):
                logits, pages = forward_paged_decode(
                    cfg, params, tokens, pages, page_tables, lengths
                )
                return logits, pages

            self._decode_paged = jax.jit(paged_step, donate_argnums=(1,))
            self._write_pages = jax.jit(write_prompt_to_pages,
                                        donate_argnums=(0,))
        else:
            self.cache = init_cache(model_config, B, S)

        # compile once: batched single-token decode (slab layout)
        def decode_step(params, cache, tokens, lengths):
            logits, cache = forward_cached(cfg, params, tokens, cache,
                                           lengths)
            return logits[:, -1, :], cache

        if not self.paged:
            self._decode = jax.jit(decode_step, donate_argnums=(1,))

        # multi-step decode: `chunk` tokens per dispatch, sampling
        # (greedy / temperature) on device inside the scan
        chunk = max(1, self.ecfg.decode_chunk)

        def _sample_on_device(logits, temps, key):
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            sampled = jax.random.categorical(key, scaled)
            return jnp.where(temps <= 0.0, greedy,
                             sampled).astype(jnp.int32)

        # The multi-step fns RETURN their token/length feedback so the
        # host can chain ticks device-to-device: on a tunneled chip the
        # d2h readback dominates the tick (~24 ms measured vs ~0.1 ms
        # dispatch/upload), so the loop pipelines — dispatch tick N,
        # async-copy its tokens, and only then process tick N-1's.
        if chunk > 1 and not self.paged:
            def decode_multi(params, cache, tokens, lengths, active,
                             temps, key):
                def step(carry, k):
                    cache, toks, lens = carry
                    logits, cache = forward_cached(
                        cfg, params, toks, cache, lens)
                    tok = _sample_on_device(logits[:, -1, :], temps, k)
                    lens = lens + active
                    return (cache, tok[:, None], lens), tok

                keys = jax.random.split(key, chunk)
                (cache, last, lens), toks = jax.lax.scan(
                    step, (cache, tokens, lengths), keys)
                return toks, last, lens, cache  # toks [chunk, B]

            self._decode_multi = jax.jit(decode_multi,
                                         donate_argnums=(1,))
        if chunk > 1 and self.paged:
            from ..models.llama import forward_paged_decode as _fpd

            def decode_multi_paged(params, pages, tokens, page_tables,
                                   lengths, active, temps, key):
                def step(carry, k):
                    pages, toks, lens = carry
                    logits, pages = _fpd(
                        cfg, params, toks, pages, page_tables, lens)
                    tok = _sample_on_device(logits, temps, k)
                    lens = lens + active
                    return (pages, tok[:, None], lens), tok

                keys = jax.random.split(key, chunk)
                (pages, last, lens), toks = jax.lax.scan(
                    step, (pages, tokens, lengths), keys)
                return toks, last, lens, pages

            self._decode_multi_paged = jax.jit(decode_multi_paged,
                                               donate_argnums=(1,))
        # device-resident (last_tokens, lengths) chained between multi-
        # step ticks; None = host state changed, re-upload next tick
        self._dev_state = None
        # in-flight (tokens_device, active, chunk) from the last
        # dispatched tick, consumed after the NEXT dispatch
        self._pending_tick = None
        # admissions whose first token was sampled ON DEVICE and not yet
        # copied to the host: list of (slot, req, token_dev). The copy
        # merges into the next tick readback — an admission costs no d2h
        # round trip of its own.
        self._pending_first: list = []
        # (slot, token_dev, length) updates to fold into the device
        # chain right before the next dispatch
        self._chain_fixups: list = []
        # device-side first-token sampling + chain scatter helpers
        self._sample_first = jax.jit(
            lambda logits, temp, key: _sample_on_device(
                logits[None, :], jnp.asarray([temp]), key)[0])
        self._admit_scatter = jax.jit(
            lambda toks, lens, idx, tok, ln: (
                toks.at[idx, 0].set(tok), lens.at[idx].set(ln)))
        self._sample_base_key = jax.random.PRNGKey(seed ^ 0x5EED)
        self._tick_counter = 0

        # prefill per bucket, single slot (both layouts)
        def prefill(params, cache1, tokens, true_len):
            zero = jnp.zeros((1,), dtype=jnp.int32)
            logits, cache1 = forward_cached(cfg, params, tokens, cache1,
                                            zero)
            last = logits[0, true_len - 1, :]
            return last, cache1

        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        # batched prefill: one dispatch per same-bucket admission group
        def prefill_batch(params, cacheB, tokens, true_lens):
            zeros = jnp.zeros((tokens.shape[0],), dtype=jnp.int32)
            logits, cacheB = forward_cached(cfg, params, tokens, cacheB,
                                            zeros)
            last = logits[jnp.arange(tokens.shape[0]), true_lens - 1, :]
            return last, cacheB

        self._prefill_batch = jax.jit(prefill_batch, donate_argnums=(1,))
        if self.paged:
            from ..models.llama import write_prompts_to_pages

            self._write_pages_batch = jax.jit(
                write_prompts_to_pages, donate_argnums=(0,))
        else:
            def scatter_slots(cache, cacheB, idx):
                return {
                    "k": cache["k"].at[:, idx].set(cacheB["k"]),
                    "v": cache["v"].at[:, idx].set(cacheB["v"]),
                }

            self._scatter_slots = jax.jit(scatter_slots,
                                          donate_argnums=(0,))

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        # head-of-line request whose page reservation is pending: retried
        # before the queue so big requests aren't starved by later small
        # ones grabbing freed pages
        self._parked: Optional[_Request] = None
        self._next_rid = 0
        self._rid_lock = threading.Lock()
        self._stop = threading.Event()
        self._precompiled = threading.Event()
        if self.ecfg.precompile_prefill:
            # background: blocking the constructor would starve the
            # replica's health checks and get it killed mid-boot.
            # Callers gate traffic on is_ready() (LLMServer.ready) so
            # steady-state serving never races compiles for the chip.
            threading.Thread(target=self._precompile_prefill_shapes,
                             daemon=True).start()
        else:
            self._precompiled.set()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def is_ready(self) -> bool:
        return self._precompiled.is_set()

    def wait_ready(self, timeout: float = 600.0) -> bool:
        return self._precompiled.wait(timeout)

    def _precompile_prefill_shapes(self):
        """Compile the prefill FORWARD shapes the engine will actually
        dispatch: the singleton path per bucket, plus each power-of-two
        group size up to prefill_batch. Throwaway caches only — live
        cache/pages state is never donated from this thread (the
        scheduler loop runs concurrently). The small KV-scatter
        compiles still happen on first use; forwards dominate."""
        import jax.numpy as jnp

        from ..models.llama import init_cache

        sizes = [1]
        b = 2
        cap = 1 << (max(1, self.ecfg.prefill_batch).bit_length() - 1)
        while b <= min(self.ecfg.max_batch_size, cap):
            sizes.append(b)
            b *= 2
        for bucket in self.ecfg.prefill_buckets:
            if bucket > self.ecfg.max_seq_len:
                continue
            # singleton groups run the single-prefill jit
            cache1 = init_cache(self.cfg, 1, self.ecfg.max_seq_len)
            self._prefill(
                self.params, cache1,
                jnp.zeros((1, bucket), jnp.int32), np.int32(1),
            )
            for bp in sizes[1:]:
                cacheB = init_cache(self.cfg, bp, self.ecfg.max_seq_len)
                self._prefill_batch(
                    self.params, cacheB,
                    jnp.zeros((bp, bucket), jnp.int32),
                    jnp.ones((bp,), jnp.int32),
                )
        self._precompiled.set()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def generate_async(self, prompt_tokens: List[int],
                       params: Optional[SamplingParams] = None) -> _Request:
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams())
        if len(req.prompt) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}"
            )
        self._queue.put(req)
        return req

    def generate_prefilled_async(
        self,
        prompt_tokens: List[int],
        kv: Dict[str, Any],  # {k, v: [L, 1, bucket, Hkv, D]}
        first_token: int,
        params: Optional[SamplingParams] = None,
    ) -> _Request:
        """Admit a request whose prefill ran on another replica
        (prefill/decode disaggregation)."""
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt_tokens, params or SamplingParams(),
                       prefilled=(kv, first_token))
        self._queue.put(req)
        return req

    def generate(self, prompt_tokens: List[int],
                 params: Optional[SamplingParams] = None,
                 timeout: float = 300.0) -> GenerationResult:
        req = self.generate_async(prompt_tokens, params)
        if not req.event.wait(timeout):
            raise TimeoutError(f"generation {req.rid} timed out")
        return req.result

    def generate_batch(self, prompts: List[List[int]],
                       params: Optional[SamplingParams] = None,
                       timeout: float = 600.0) -> List[GenerationResult]:
        reqs = [self.generate_async(p, params) for p in prompts]
        out = []
        deadline = time.time() + timeout
        for r in reqs:
            if not r.event.wait(max(0.0, deadline - time.time())):
                raise TimeoutError("batch generation timed out")
            out.append(r.result)
        return out

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def stats(self) -> Dict[str, Any]:
        out = {
            "active": sum(s is not None for s in self.slots),
            "waiting": self._queue.qsize(),
            "max_batch": self.ecfg.max_batch_size,
            "kv_layout": self.ecfg.kv_layout,
        }
        if self.paged:
            out["free_pages"] = len(self.free_pages)
            out["total_pages"] = self.num_pages - 1  # minus scratch
        return out

    # ------------------------------------------------------------------
    # scheduler loop
    # ------------------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.ecfg.prefill_buckets:
            if n <= b and b <= self.ecfg.max_seq_len:
                return b
        return self.ecfg.max_seq_len

    def _loop(self):
        jnp = self._jnp
        while not self._stop.is_set():
            try:
                self._loop_once(jnp)
            except Exception:  # noqa: BLE001 — scheduler must survive
                import traceback

                err = traceback.format_exc()
                for i, req in enumerate(self.slots):
                    if req is not None:
                        self._finish_with_error(i, err)
                # decode/prefill donate the cache buffer (donate_argnums):
                # an exception after donation leaves the cache permanently
                # invalid, which would fail every future request. Rebuild.
                if self.paged:
                    from ..models.llama import init_paged_cache

                    self.pages = init_paged_cache(
                        self.cfg, self.num_pages, self.ecfg.page_size
                    )
                    self.free_pages = list(range(1, self.num_pages))
                    self._slot_pages = [
                        [] for _ in range(self.ecfg.max_batch_size)
                    ]
                    self.page_tables[:] = 0
                else:
                    from ..models.llama import init_cache

                    self.cache = init_cache(
                        self.cfg, self.ecfg.max_batch_size,
                        self.ecfg.max_seq_len,
                    )
                self.lengths[:] = 0
                self.slots = [None] * self.ecfg.max_batch_size
                # the pipelined tick and device feedback chain reference
                # the donated (now rebuilt) buffers — reset both
                self._pending_tick = None
                self._dev_state = None
                time.sleep(0.05)

    def _finish_with_error(self, i: int, err: str):
        req = self.slots[i]
        req.result = GenerationResult(
            request_id=req.rid,
            prompt_tokens=req.prompt,
            token_ids=list(req.generated),
            finish_reason=f"error: {err.splitlines()[-1][:200]}",
            latency_s=time.time() - req.submit_time,
        )
        self.slots[i] = None
        self.lengths[i] = 0
        self._free_slot_pages(i)
        req.event.set()

    def _loop_once(self, jnp):
            self._admit()
            if self._dev_state is None:
                # broken chain (host-sampled admission, single-step
                # fallback, or error recovery): the host mirrors must
                # fold in EVERY dispatched tick before they are
                # re-uploaded, or the next tick replays the in-flight
                # one (double-appending its tokens)
                self._drain_pending_tick()
            active = [i for i, s in enumerate(self.slots) if s is not None]
            if not active:
                if self._pending_tick is not None:
                    self._drain_pending_tick()
                elif not admitted:
                    time.sleep(0.002)
                return
            last_tokens = np.zeros(
                (self.ecfg.max_batch_size, 1), dtype=np.int32
            )
            for i in active:
                req = self.slots[i]
                last_tokens[i, 0] = (
                    req.generated[-1] if req.generated else req.prompt[-1]
                )
            chunk = max(1, self.ecfg.decode_chunk)
            # with a tick in flight the device lengths run ahead of the
            # host mirror by up to one chunk — keep that margin in bounds
            margin = chunk * (2 if self._pending_tick is not None else 1)
            use_multi = (
                chunk > 1
                and all(
                    self.slots[i].params.top_k in (0, None)
                    and self.slots[i].params.seed is None
                    for i in active
                )
                # overshoot inside the chunk must stay within bounds
                and int(self.lengths[active].max()) + margin
                < self.ecfg.max_seq_len
            )
            if use_multi:
                self._decode_chunk(jnp, active, last_tokens, chunk)
                return
            # single batched decode step for every active slot: host
            # sampling needs host lengths to be exact — drain the
            # pipelined tick and resolve device-held first tokens first
            self._drain_pending_tick()
            self._resolve_pending_first()
            self._dev_state = None
            self._chain_fixups.clear()
            active = [i for i, s in enumerate(self.slots) if s is not None]
            if not active:
                return
            for i in active:
                req = self.slots[i]
                last_tokens[i, 0] = (
                    req.generated[-1] if req.generated else req.prompt[-1]
                )
            if self.paged:
                logits, self.pages = self._decode_paged(
                    self.params,
                    self.pages,
                    jnp.asarray(last_tokens),
                    jnp.asarray(self.page_tables),
                    jnp.asarray(self.lengths),
                )
            else:
                logits, self.cache = self._decode(
                    self.params,
                    self.cache,
                    jnp.asarray(last_tokens),
                    jnp.asarray(self.lengths),
                )
            logits_np = np.asarray(logits)
            self.lengths[active] += 1
            now = time.time()
            for i in active:
                req = self.slots[i]
                tok = self._sample(logits_np[i], req.params)
                req.generated.append(int(tok))
                if req.first_token_time is None:
                    req.first_token_time = now
                self._maybe_finish(i)

    def _decode_chunk(self, jnp, active, last_tokens, chunk):
        """Multi-step decode, PIPELINED: `chunk` tokens per dispatch with
        on-device sampling, token/length feedback chained device-side
        (no per-tick upload), and the token readback of tick N consumed
        only after tick N+1 is dispatched — the ~24 ms tunneled-d2h
        latency overlaps the next tick's compute instead of serializing
        with it. Tokens past a request's stop are discarded (the cache
        positions they wrote are beyond the request's final length and
        are never read; device lengths for continuing slots stay exact
        because only finishing conditions truncate a chunk)."""
        jax = self._jax
        B = self.ecfg.max_batch_size
        active_mask = np.zeros(B, dtype=np.int32)
        active_mask[active] = 1
        temps = np.zeros(B, dtype=np.float32)
        for i in active:
            temps[i] = self.slots[i].params.temperature
        self._tick_counter += 1
        key = jax.random.fold_in(self._sample_base_key,
                                 self._tick_counter)
        if self._dev_state is not None:
            tokens_in, lengths_in = self._dev_state
        else:
            tokens_in = jnp.asarray(last_tokens)
            lengths_in = jnp.asarray(self.lengths)
        # fold freshly admitted slots into the chain ON DEVICE (their
        # first tokens exist only there; see _pending_first)
        if self._chain_fixups:
            for slot, tok_dev, ln in self._chain_fixups:
                tokens_in, lengths_in = self._admit_scatter(
                    tokens_in, lengths_in, slot, tok_dev, ln)
            self._chain_fixups.clear()
        if self.paged:
            toks, last, lens, self.pages = self._decode_multi_paged(
                self.params, self.pages, tokens_in,
                jnp.asarray(self.page_tables), lengths_in,
                jnp.asarray(active_mask), jnp.asarray(temps), key,
            )
        else:
            toks, last, lens, self.cache = self._decode_multi(
                self.params, self.cache, tokens_in,
                lengths_in, jnp.asarray(active_mask),
                jnp.asarray(temps), key,
            )
        self._dev_state = (last, lens)
        try:
            toks.copy_to_host_async()
        except Exception:
            pass  # backend without async copy: np.asarray blocks later
        # capture request IDENTITY, not just slot index: a slot can be
        # freed and re-admitted between this dispatch and the consume,
        # and the new occupant must not inherit the old one's tokens
        prev, self._pending_tick = (
            self._pending_tick,
            (toks, [(i, self.slots[i]) for i in active], chunk))
        if prev is not None:
            self._consume_tick(*prev)

    def _drain_pending_tick(self):
        prev, self._pending_tick = self._pending_tick, None
        if prev is not None:
            self._consume_tick(*prev)
        elif self._pending_first:
            self._resolve_pending_first()

    def _resolve_pending_first(self):
        """Copy device-held first tokens to the host (outside a tick
        readback — used by the single-step fallback and idle drains)."""
        pend, self._pending_first = self._pending_first, []
        for slot, req, tok_dev in pend:
            if self.slots[slot] is not req:
                continue
            req.generated.append(int(np.asarray(tok_dev)))
            self._maybe_finish(slot)

    def _consume_tick(self, toks_dev, active, chunk):
        """Fold a completed tick's tokens into host state. Device-held
        first tokens of freshly admitted slots merge into the SAME d2h
        transfer (one concatenated array), so admissions never pay
        their own tunnel round trip. Finished slots do NOT break the
        device chain: their rows go inactive, and the garbage their
        stale lengths produce lands on the paged layout's sacrificial
        page 0 / the dead slab rows, both rewritten at the next
        admission."""
        jnp = self._jnp
        pend, self._pending_first = self._pending_first, []
        if pend:
            firsts = jnp.stack([t for _s, _r, t in pend])
            merged = np.asarray(
                jnp.concatenate([toks_dev.reshape(-1),
                                 firsts.astype(toks_dev.dtype)]))
            B = self.ecfg.max_batch_size
            toks_np = merged[: chunk * B].reshape(chunk, B)
            first_np = merged[chunk * B:]
            # first tokens PRECEDE this tick's tokens for their slots
            # (the tick containing those slots is still in flight or is
            # this very one — fold order preserves sequence order)
            for (slot, req, _t), tok in zip(pend, first_np):
                if self.slots[slot] is not req:
                    continue
                req.generated.append(int(tok))
                self._maybe_finish(slot)
        else:
            toks_np = np.asarray(toks_dev)  # [chunk, B]
        now = time.time()
        for i, req in active:
            if req is None or self.slots[i] is not req:
                continue  # freed (or slot re-admitted) since dispatch
            consumed = 0
            for step in range(chunk):
                req.generated.append(int(toks_np[step, i]))
                consumed += 1
                if req.first_token_time is None:
                    req.first_token_time = now
                if self._is_finished(req):
                    break
            self.lengths[i] += consumed
            self._maybe_finish(i)

    def _admit(self) -> bool:
        jnp = self._jnp
        admitted = False
        to_prefill: list = []
        for i in range(self.ecfg.max_batch_size):
            if self.slots[i] is not None:
                continue
            if self._parked is not None:
                req, self._parked = self._parked, None
            else:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
            bucket = self._bucket(len(req.prompt))
            if self.paged and not self._reserve_pages(i, req, bucket):
                ps = self.ecfg.page_size
                horizon = min(
                    len(req.prompt) + req.params.max_tokens + 1,
                    self.ecfg.max_seq_len,
                )
                need = max(bucket // ps, -(-horizon // ps))
                if need > self.num_pages - 1:
                    # can never fit: fail fast instead of spinning
                    req.result = GenerationResult(
                        request_id=req.rid,
                        prompt_tokens=req.prompt,
                        token_ids=[],
                        finish_reason=(
                            f"error: request needs {need} KV pages but "
                            f"the engine has {self.num_pages - 1}"
                        ),
                        latency_s=time.time() - req.submit_time,
                    )
                    req.event.set()
                    continue
                # wait head-of-line until pages free up
                self._parked = req
                break
            if req.prefilled is not None:
                # disaggregated admission: KV arrived from a prefill
                # replica (device transport); install it and skip the
                # prefill compute entirely
                kv, first_tok = req.prefilled
                req.prefilled = None  # free the transferred copy
                kvb = kv["k"].shape[2]
                if self.paged:
                    ps = self.ecfg.page_size
                    rows = jnp.asarray(
                        self._slot_pages[i][: kvb // ps],
                        dtype=jnp.int32,
                    )
                    self.pages = self._write_pages(self.pages, kv, rows)
                else:
                    self.cache = {
                        "k": self.cache["k"].at[:, i, :kvb].set(
                            kv["k"][:, 0]),
                        "v": self.cache["v"].at[:, i, :kvb].set(
                            kv["v"][:, 0]),
                    }
                self.lengths[i] = len(req.prompt)
                req.generated.append(int(first_tok))
                req.first_token_time = req.first_token_time or time.time()
                self.slots[i] = req
                admitted = True
                self._maybe_finish(i)
                if self.slots[i] is not None:
                    # splice the transferred first token into the live
                    # decode chain (value is host-known; upload is cheap)
                    self._chain_fixups.append(
                        (i, jnp.asarray(int(first_tok), jnp.int32),
                         len(req.prompt)))
                continue
            to_prefill.append((i, req, bucket))
            self.slots[i] = req  # reserve the slot now
            admitted = True
        if to_prefill:
            self._prefill_groups(to_prefill)
        return admitted

    def _prefill_groups(self, to_prefill):
        """Prefill admitted requests grouped by bucket: ONE forward
        dispatch (and one KV scatter) per group instead of one per
        request (the reference gets this from vLLM's batched prefill;
        on dispatch-latency-bound backends it's the admission
        bottleneck)."""
        jnp = self._jnp
        groups: Dict[int, list] = {}
        for item in to_prefill:
            groups.setdefault(item[2], []).append(item)
        # quantize group sizes to powers of two (7 -> 4+2+1): every
        # distinct (size, bucket) shape is a separate XLA compile, so
        # arbitrary sizes would stall the data plane on fresh compiles
        # mid-traffic
        quantized: list = []
        for bucket, items in groups.items():
            pos = 0
            while pos < len(items):
                take = 1 << ((len(items) - pos).bit_length() - 1)
                # cap is rounded DOWN to a power of two: every shape
                # dispatched here must be in the precompiled set
                cap = 1 << (max(1, self.ecfg.prefill_batch)
                            .bit_length() - 1)
                take = min(take, cap)
                quantized.append((bucket, items[pos:pos + take]))
                pos += take
        for bucket, items in quantized:
            Bp = len(items)
            if Bp == 1:
                # singleton: the original single-prefill path (identical
                # cost profile to pre-batching behavior)
                self._prefill_one(*items[0])
                continue
            tokens = np.zeros((Bp, bucket), dtype=np.int32)
            true_lens = np.zeros((Bp,), dtype=np.int32)
            for j, (_i, req, _b) in enumerate(items):
                tokens[j, : len(req.prompt)] = req.prompt
                true_lens[j] = len(req.prompt)
            from ..models.llama import init_cache

            cacheB = init_cache(self.cfg, Bp, self.ecfg.max_seq_len)
            last_logits, cacheB = self._prefill_batch(
                self.params, cacheB, jnp.asarray(tokens),
                jnp.asarray(true_lens),
            )
            if self.paged:
                ps = self.ecfg.page_size
                nb = bucket // ps
                rows = np.stack([
                    np.asarray(self._slot_pages[i][:nb], dtype=np.int32)
                    for i, _r, _b in items
                ])
                sliced = {
                    "k": cacheB["k"][:, :, :bucket],
                    "v": cacheB["v"][:, :, :bucket],
                }
                self.pages = self._write_pages_batch(
                    self.pages, sliced, jnp.asarray(rows))
            else:
                idx = jnp.asarray([i for i, _r, _b in items],
                                  dtype=jnp.int32)
                self.cache = self._scatter_slots(
                    self.cache, cacheB, idx)
            self._finish_admissions(
                [(i, req) for i, req, _b in items], last_logits)

    def _prefill_one(self, i, req, bucket):
        jnp = self._jnp
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, : len(req.prompt)] = req.prompt
        from ..models.llama import init_cache

        cache1 = init_cache(self.cfg, 1, self.ecfg.max_seq_len)
        last_logits, cache1 = self._prefill(
            self.params, cache1, jnp.asarray(tokens),
            np.int32(len(req.prompt)),
        )
        if self.paged:
            ps = self.ecfg.page_size
            nb = bucket // ps
            rows = jnp.asarray(self._slot_pages[i][:nb], dtype=jnp.int32)
            sliced = {
                "k": cache1["k"][:, :, :bucket],
                "v": cache1["v"][:, :, :bucket],
            }
            self.pages = self._write_pages(self.pages, sliced, rows)
        else:
            self.cache = {
                "k": self.cache["k"].at[:, i].set(cache1["k"][:, 0]),
                "v": self.cache["v"].at[:, i].set(cache1["v"][:, 0]),
            }
        self._finish_admissions([(i, req)], last_logits[None, :])

    def _finish_admissions(self, items, last_logits):
        """Install admitted requests' first tokens. Device-sampleable
        requests (greedy/temperature) sample ON DEVICE, defer the host
        copy to the next tick readback, and scatter straight into the
        decode feedback chain — an admission costs zero extra d2h round
        trips. Host-sampled requests (top_k / per-request seed) read the
        logits back and break the chain (rare path)."""
        jax = self._jax
        jnp = self._jnp
        logits_np = None
        now = time.time()
        for j, (i, req) in enumerate(items):
            self.lengths[i] = len(req.prompt)
            req.first_token_time = now
            if req.params.top_k in (0, None) and req.params.seed is None:
                self._tick_counter += 1
                key = jax.random.fold_in(self._sample_base_key,
                                         self._tick_counter)
                tok_dev = self._sample_first(
                    last_logits[j], np.float32(req.params.temperature),
                    key)
                self._pending_first.append((i, req, tok_dev))
                self._chain_fixups.append(
                    (i, tok_dev, len(req.prompt)))
            else:
                if logits_np is None:
                    logits_np = np.asarray(last_logits)
                tok = self._sample(logits_np[j], req.params)
                req.generated.append(int(tok))
                self._dev_state = None  # host mirrors are authoritative
                self._maybe_finish(i)

    def _reserve_pages(self, i: int, req: "_Request", bucket: int) -> bool:
        """Allocate exactly the pages this request can ever touch:
        max(prefill bucket, prompt+max_tokens+1) rounded to pages."""
        ps = self.ecfg.page_size
        horizon = min(len(req.prompt) + req.params.max_tokens + 1,
                      self.ecfg.max_seq_len)
        need = max(bucket // ps, -(-horizon // ps))
        if len(self.free_pages) < need:
            return False
        pages = [self.free_pages.pop() for _ in range(need)]
        self._slot_pages[i] = pages
        row = np.zeros(self.page_tables.shape[1], dtype=np.int32)
        row[: len(pages)] = pages
        self.page_tables[i] = row
        return True

    def _free_slot_pages(self, i: int):
        if self.paged:
            self.free_pages.extend(self._slot_pages[i])
            self._slot_pages[i] = []
            self.page_tables[i] = 0

    def _sample(self, logits: np.ndarray, params: SamplingParams) -> int:
        if params.temperature <= 0.0:
            return int(np.argmax(logits))
        logits = logits / params.temperature
        if params.top_k and params.top_k > 0:
            kth = np.partition(logits, -params.top_k)[-params.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        logits = logits - logits.max()
        p = np.exp(logits)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _is_finished(self, req: "_Request") -> bool:
        return bool(
            (req.generated
             and req.generated[-1] in req.params.stop_token_ids)
            or len(req.generated) >= req.params.max_tokens
        )

    def _maybe_finish(self, i: int):
        req = self.slots[i]
        reason = None
        if self._is_finished(req):
            reason = (
                "stop"
                if req.generated[-1] in req.params.stop_token_ids
                else "length"
            )
        elif self.lengths[i] + 1 >= self.ecfg.max_seq_len:
            reason = "max_seq_len"
        if reason is None:
            return
        now = time.time()
        req.result = GenerationResult(
            request_id=req.rid,
            prompt_tokens=req.prompt,
            token_ids=list(req.generated),
            finish_reason=reason,
            ttft_s=(req.first_token_time or now) - req.submit_time,
            latency_s=now - req.submit_time,
        )
        self.slots[i] = None
        self.lengths[i] = 0
        self._free_slot_pages(i)
        req.event.set()
