"""Batch inference over ray_tpu.data (reference: ray.data.llm
build_llm_processor, data/llm.py:248)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class _EngineUDF:
    """Stateful map_batches UDF hosting one engine per actor."""

    def __init__(self, model_config: Optional[dict],
                 engine_config: Optional[dict], sampling: Optional[dict]):
        from ..models.llama import LlamaConfig
        from .engine import EngineConfig, LLMEngine, SamplingParams

        model_config = dict(model_config or {})
        preset = model_config.pop("preset", "tiny")
        cfg = getattr(LlamaConfig, preset)(**model_config)
        self.engine = LLMEngine(
            cfg, engine_config=EngineConfig(**(engine_config or {}))
        )
        self.params = SamplingParams(**(sampling or {}))

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        prompts = [list(map(int, p)) for p in batch["prompt"]]
        results = self.engine.generate_batch(prompts, self.params)
        return {
            "prompt": [list(p) for p in prompts],
            "generated": [r.token_ids for r in results],
            "finish_reason": [r.finish_reason for r in results],
        }


def batch_generate(
    ds,
    *,
    model_config: Optional[dict] = None,
    engine_config: Optional[dict] = None,
    sampling: Optional[dict] = None,
    concurrency: int = 1,
    batch_size: int = 8,
):
    """ds rows must have a 'prompt' column of token-id lists. Returns a
    Dataset with 'generated' + 'finish_reason' columns."""
    return ds.map_batches(
        _EngineUDF,
        fn_constructor_args=(model_config, engine_config, sampling),
        concurrency=concurrency,
        batch_size=batch_size,
        batch_format="numpy",
    )
