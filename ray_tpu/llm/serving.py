"""LLM serving: OpenAI-style deployment on ray_tpu.serve.

Reference: ray.serve.llm — LLMServer deployment wrapping the engine
(llm/_internal/serve/deployments/llm/llm_server.py) + OpenAI-compatible
API (configs/openai_api_models.py). Completions/chat payloads map onto the
native engine; prompts are token-id lists, or strings when a HF tokenizer
name is configured (transformers is available in-image).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class LLMServer:
    """User-facing deployment class (wrap with serve.deployment)."""

    def __init__(self, model_config: Optional[dict] = None,
                 engine_config: Optional[dict] = None,
                 tokenizer: Optional[str] = None,
                 params_checkpoint: Optional[str] = None):
        from ..models.llama import LlamaConfig
        from .engine import EngineConfig, LLMEngine

        model_config = model_config or {}
        preset = model_config.pop("preset", "tiny")
        factory = getattr(LlamaConfig, preset)
        cfg = factory(**model_config)
        params = None
        if params_checkpoint:
            from ..train.checkpoint import Checkpoint

            params = Checkpoint(params_checkpoint).load_state()
        self.engine = LLMEngine(
            cfg,
            params=params,
            engine_config=EngineConfig(**(engine_config or {})),
        )
        self.tokenizer = None
        if tokenizer:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(tokenizer)

    def ready(self) -> bool:
        """True once boot-time compiles finished (gate traffic on it;
        see EngineConfig.precompile_prefill)."""
        return self.engine.is_ready()

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.tokenizer is None:
            raise ValueError(
                "string prompts require a tokenizer; pass token-id lists"
            )
        return self.tokenizer.encode(prompt)

    def _decode_text(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(token_ids)

    def _parse(self, payload: Dict[str, Any]):
        from .engine import SamplingParams

        if "messages" in payload:  # chat
            if self.tokenizer is not None and hasattr(
                self.tokenizer, "apply_chat_template"
            ):
                prompt = self.tokenizer.apply_chat_template(
                    payload["messages"], tokenize=True
                )
            else:
                prompt = []
                for m in payload["messages"]:
                    prompt.extend(self._encode(m["content"]))
        else:
            prompt = self._encode(payload.get("prompt", []))
        params = SamplingParams(
            max_tokens=int(payload.get("max_tokens", 64)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            stop_token_ids=tuple(payload.get("stop_token_ids", ())),
        )
        return prompt, params

    async def __call__(self, payload: Dict[str, Any]):
        """OpenAI-ish: supports /v1/completions-shaped payloads and chat
        messages (flattened). With "stream": true, returns an async
        generator of OpenAI chunk dicts ending with "[DONE]" — the serve
        proxy SSE-frames each item (reference: ray.serve.llm openai
        streaming responses)."""
        prompt, params = self._parse(payload)
        if payload.get("stream"):
            return self._stream_chunks(prompt, params)
        result = await self.engine.agenerate(prompt, params)
        text = self._decode_text(result.token_ids)
        choice: Dict[str, Any] = {
            "index": 0,
            "token_ids": result.token_ids,
            "finish_reason": result.finish_reason,
        }
        if text is not None:
            choice["text"] = text
        return {
            "id": f"cmpl-{result.request_id}",
            "object": "text_completion",
            "created": int(time.time()),
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(prompt),
                "completion_tokens": len(result.token_ids),
                "total_tokens": len(prompt) + len(result.token_ids),
            },
            "metrics": {
                "ttft_s": result.ttft_s,
                "latency_s": result.latency_s,
            },
        }

    async def _stream_chunks(self, prompt, params):
        """OpenAI streaming chunks: one per token, a final chunk with
        finish_reason + usage, then the "[DONE]" sentinel."""
        rid: Any = ""
        toks: List[int] = []
        emitted = 0  # chars of decoded text already streamed
        async for ev in self.engine.astream(prompt, params):
            if "token" in ev:
                tok = ev["token"]
                rid = ev.get("rid", rid)
                toks.append(tok)
                chunk: Dict[str, Any] = {
                    "id": f"cmpl-{rid}",
                    "object": "text_completion.chunk",
                    "created": int(time.time()),
                    "choices": [{
                        "index": 0,
                        "token_ids": [tok],
                        "finish_reason": None,
                    }],
                }
                if self.tokenizer is not None:
                    # Incremental detokenization: decode the prefix so
                    # far and emit only the NEW suffix, holding back
                    # while the tail is an incomplete UTF-8 sequence —
                    # a codepoint whose bytes span two BPE tokens must
                    # never stream as replacement chars (vLLM's
                    # incremental detokenizer does the same). Decoding
                    # the full prefix per token is O(n²) in stream
                    # length; acceptable at completion sizes, window it
                    # if multi-thousand-token streams become the norm.
                    text = self.tokenizer.decode(toks)
                    if text.endswith("�"):
                        chunk["choices"][0]["text"] = ""
                    else:
                        chunk["choices"][0]["text"] = text[emitted:]
                        emitted = len(text)
                yield chunk
            else:
                result = ev["done"]
                final_choice: Dict[str, Any] = {
                    "index": 0,
                    "token_ids": [],
                    "finish_reason": result.finish_reason,
                }
                if self.tokenizer is not None:
                    # flush any text held back by the incomplete-UTF-8
                    # guard above
                    text = self.tokenizer.decode(result.token_ids)
                    final_choice["text"] = text[emitted:]
                yield {
                    "id": f"cmpl-{result.request_id}",
                    "object": "text_completion.chunk",
                    "created": int(time.time()),
                    "choices": [final_choice],
                    "usage": {
                        "prompt_tokens": len(prompt),
                        "completion_tokens": len(result.token_ids),
                        "total_tokens": len(prompt) + len(result.token_ids),
                    },
                    "metrics": {
                        "ttft_s": result.ttft_s,
                        "latency_s": result.latency_s,
                    },
                }
        yield "[DONE]"

    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()


def build_openai_app(
    model_config: Optional[dict] = None,
    engine_config: Optional[dict] = None,
    tokenizer: Optional[str] = None,
    *,
    num_replicas: int = 1,
    route_prefix: str = "/v1",
    ray_actor_options: Optional[dict] = None,
):
    """Returns a serve Application exposing /v1/completions-style HTTP."""
    from .. import serve

    if ray_actor_options is None and _tpu_visible():
        # one TPU chip per replica (process-exclusive on TPU VMs)
        ray_actor_options = {"num_tpus": 1}
    dep = serve.deployment(
        LLMServer,
        name="LLMServer",
        num_replicas=num_replicas,
        route_prefix=route_prefix,
        max_ongoing_requests=256,
        ray_actor_options=ray_actor_options,
    )
    return dep.bind(model_config, engine_config, tokenizer)


def _tpu_visible() -> bool:
    import os

    return bool(os.environ.get("TPU_CHIPS")
                or os.environ.get("PALLAS_AXON_POOL_IPS", "").strip())
