"""LLM serving: OpenAI-style deployment on ray_tpu.serve.

Reference: ray.serve.llm — LLMServer deployment wrapping the engine
(llm/_internal/serve/deployments/llm/llm_server.py) + OpenAI-compatible
API (configs/openai_api_models.py). Completions/chat payloads map onto the
native engine; prompts are token-id lists, or strings when a HF tokenizer
name is configured (transformers is available in-image).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class LLMServer:
    """User-facing deployment class (wrap with serve.deployment)."""

    def __init__(self, model_config: Optional[dict] = None,
                 engine_config: Optional[dict] = None,
                 tokenizer: Optional[str] = None,
                 params_checkpoint: Optional[str] = None):
        from ..models.llama import LlamaConfig
        from .engine import EngineConfig, LLMEngine

        model_config = model_config or {}
        preset = model_config.pop("preset", "tiny")
        factory = getattr(LlamaConfig, preset)
        cfg = factory(**model_config)
        params = None
        if params_checkpoint:
            from ..train.checkpoint import Checkpoint

            params = Checkpoint(params_checkpoint).load_state()
        self.engine = LLMEngine(
            cfg,
            params=params,
            engine_config=EngineConfig(**(engine_config or {})),
        )
        self.tokenizer = None
        if tokenizer:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(tokenizer)

    def ready(self) -> bool:
        """True once boot-time compiles finished (gate traffic on it;
        see EngineConfig.precompile_prefill)."""
        return self.engine.is_ready()

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.tokenizer is None:
            raise ValueError(
                "string prompts require a tokenizer; pass token-id lists"
            )
        return self.tokenizer.encode(prompt)

    def _decode_text(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(token_ids)

    async def __call__(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI-ish: supports /v1/completions-shaped payloads and chat
        messages (flattened)."""
        from .engine import SamplingParams

        if "messages" in payload:  # chat
            if self.tokenizer is not None and hasattr(
                self.tokenizer, "apply_chat_template"
            ):
                prompt = self.tokenizer.apply_chat_template(
                    payload["messages"], tokenize=True
                )
            else:
                prompt = []
                for m in payload["messages"]:
                    prompt.extend(self._encode(m["content"]))
        else:
            prompt = self._encode(payload.get("prompt", []))
        params = SamplingParams(
            max_tokens=int(payload.get("max_tokens", 64)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            stop_token_ids=tuple(payload.get("stop_token_ids", ())),
        )
        result = await self.engine.agenerate(prompt, params)
        text = self._decode_text(result.token_ids)
        choice: Dict[str, Any] = {
            "index": 0,
            "token_ids": result.token_ids,
            "finish_reason": result.finish_reason,
        }
        if text is not None:
            choice["text"] = text
        return {
            "id": f"cmpl-{result.request_id}",
            "object": "text_completion",
            "created": int(time.time()),
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(prompt),
                "completion_tokens": len(result.token_ids),
                "total_tokens": len(prompt) + len(result.token_ids),
            },
            "metrics": {
                "ttft_s": result.ttft_s,
                "latency_s": result.latency_s,
            },
        }

    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()


def build_openai_app(
    model_config: Optional[dict] = None,
    engine_config: Optional[dict] = None,
    tokenizer: Optional[str] = None,
    *,
    num_replicas: int = 1,
    route_prefix: str = "/v1",
    ray_actor_options: Optional[dict] = None,
):
    """Returns a serve Application exposing /v1/completions-style HTTP."""
    from .. import serve

    if ray_actor_options is None and _tpu_visible():
        # one TPU chip per replica (process-exclusive on TPU VMs)
        ray_actor_options = {"num_tpus": 1}
    dep = serve.deployment(
        LLMServer,
        name="LLMServer",
        num_replicas=num_replicas,
        route_prefix=route_prefix,
        max_ongoing_requests=256,
        ray_actor_options=ray_actor_options,
    )
    return dep.bind(model_config, engine_config, tokenizer)


def _tpu_visible() -> bool:
    import os

    return bool(os.environ.get("TPU_CHIPS")
                or os.environ.get("PALLAS_AXON_POOL_IPS", "").strip())
