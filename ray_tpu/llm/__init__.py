"""ray_tpu.llm — native LLM inference: continuous batching on TPU.

Reference: python/ray/llm/ (SURVEY §2.4) — but where the reference wraps
vLLM (llm/_internal/serve/deployments/llm/vllm/), the engine here is
native jax: a slot-based continuous-batching scheduler around a jitted
KV-cache decode step (models/llama.py forward_cached), bucketed prefill
compiles, and OpenAI-style serving through ray_tpu.serve.
"""
from .engine import EngineConfig, GenerationResult, LLMEngine, SamplingParams  # noqa: F401
from .serving import LLMServer, build_openai_app  # noqa: F401
from .batch import batch_generate  # noqa: F401
from .disagg import (  # noqa: F401
    DecodeReplica,
    DisaggregatedLLM,
    PrefillReplica,
)
