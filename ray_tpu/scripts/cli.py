"""ray-tpu CLI: start / stop / status / submit / jobs / timeline /
microbenchmark.

Reference: python/ray/scripts/scripts.py — `ray start` (:677), `ray stop`,
`ray status` (:2124), `ray timeline` (:2026), `ray microbenchmark`
(:2012), plus the job CLI from dashboard/modules/job/cli.py.

Invoke as ``python -m ray_tpu <command>``. Cluster bookkeeping lives in
<session_dir_root>/current_cluster.json so stop/status/submit find the
running cluster without flags.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional, Tuple


def _cluster_file() -> str:
    from ray_tpu._private.config import get_config

    root = get_config().session_dir_root
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, "current_cluster.json")


def _load_cluster() -> Optional[dict]:
    try:
        with open(_cluster_file()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _resolve_address(args) -> Tuple[str, int]:
    addr = getattr(args, "address", None) or os.environ.get(
        "RAY_TPU_ADDRESS")
    if addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    info = _load_cluster()
    if info:
        return tuple(info["gcs_address"])
    sys.exit(
        "error: no running cluster found — pass --address or run "
        "`python -m ray_tpu start --head` first"
    )


# ---------------------------------------------------------------------------
def cmd_start(args):
    from ray_tpu._private import node as node_mod

    if args.head:
        node = node_mod.Node(
            head=True,
            resources=json.loads(args.resources) if args.resources else None,
        )
    else:
        host, port = _resolve_address(args)
        node = node_mod.Node(
            head=False,
            gcs_address=(host, port),
            resources=json.loads(args.resources) if args.resources else None,
        )
    # the CLI exits but the node must keep running: detach lifecycle
    import atexit

    atexit.unregister(node.shutdown)
    pids = [p.pid for p in node._procs]
    addr_str = f"{node.gcs_address[0]}:{node.gcs_address[1]}"
    extras = []
    if args.head and getattr(args, "dashboard_port", None):
        extras.append(_spawn_service(
            ["-m", "ray_tpu.dashboard", "--address", addr_str,
             "--port", str(args.dashboard_port)],
            node.session_dir, "dashboard", "DASHBOARD_READY"))
        print(f"  dashboard:   http://127.0.0.1:{args.dashboard_port}")
    if args.head and getattr(args, "ray_client_server_port", None):
        extras.append(_spawn_service(
            ["-m", "ray_tpu.util.client", "--address", addr_str,
             "--port", str(args.ray_client_server_port)],
            node.session_dir, "client_server", "CLIENT_SERVER_READY"))
        print("  client:      ray_tpu.init(address="
              f"\"ray://127.0.0.1:{args.ray_client_server_port}\")")
    pids += extras
    info = {
        "gcs_address": list(node.gcs_address),
        "session_dir": node.session_dir,
        "node_id": node.node_id,
        "pids": pids,
        "is_head": node.is_head,
    }
    if args.head:
        with open(_cluster_file(), "w") as f:
            json.dump(info, f)
    addr = f"{node.gcs_address[0]}:{node.gcs_address[1]}"
    print(f"ray_tpu {'head' if args.head else 'worker'} node started.")
    print(f"  address:     {addr}")
    print(f"  session dir: {node.session_dir}")
    print(f"  connect:     ray_tpu.init(address=\"{addr}\")")
    if args.block:
        try:
            while all(_alive(p) for p in pids):
                time.sleep(1.0)
        except KeyboardInterrupt:
            _stop_pids(pids)


def _spawn_service(py_args, session_dir, name, ready_marker,
                   timeout=60.0) -> int:
    """Detached helper process (dashboard / client server) with its
    stdout captured in the session log dir; waits for the readiness
    line so 'start' failing is loud, not silent."""
    import subprocess
    import sys

    log_path = os.path.join(session_dir, "logs", f"{name}.log")
    log = open(log_path, "ab")
    # stdout goes STRAIGHT to the log file — a pipe would break the
    # service once the CLI (its only reader) exits; readiness is
    # detected by polling the file for the marker
    proc = subprocess.Popen(
        [sys.executable, *py_args],
        stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    log.close()
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with open(log_path, "rb") as f:
                if ready_marker.encode() in f.read():
                    return proc.pid
        except OSError:
            pass
        if proc.poll() is not None:
            raise RuntimeError(
                f"{name} exited rc={proc.returncode}; see {log_path}")
        time.sleep(0.3)
    proc.kill()
    raise RuntimeError(f"{name} not ready in {timeout}s; see {log_path}")


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _stop_pids(pids):
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + 5.0
    while time.time() < deadline and any(_alive(p) for p in pids):
        time.sleep(0.1)
    for pid in pids:
        if _alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def cmd_stop(args):
    info = _load_cluster()
    if not info:
        print("no recorded cluster; nothing to stop")
        return
    _stop_pids(info.get("pids", []))
    try:
        os.unlink(_cluster_file())
    except OSError:
        pass
    print("ray_tpu cluster stopped.")


def cmd_status(args):
    from ray_tpu._private.gcs import GcsClient

    host, port = _resolve_address(args)
    gcs = GcsClient(host, port)
    try:
        status = gcs.get_cluster_status(timeout=10.0)
    finally:
        gcs.close()
    up = int(status.get("uptime_s", 0))
    print(f"cluster at {host}:{port} — up {up // 3600}h"
          f"{(up % 3600) // 60:02d}m{up % 60:02d}s")
    nodes = status.get("nodes", [])
    alive = [n for n in nodes if n.get("alive", True)]
    print(f"nodes: {len(alive)} alive / {len(nodes)} total")
    for n in alive:
        total = n.get("total", {})
        avail = n.get("available", {})
        res = ", ".join(
            f"{k} {avail.get(k, 0):g}/{v:g}" for k, v in sorted(
                total.items()) if k != "memory"
        )
        head = " (head)" if n.get("is_head") else ""
        print(f"  {n['node_id'][:12]}{head}: {res}")
    print(f"actors: {status.get('num_actors', 0)} "
          f"(pending {status.get('num_pending_actors', 0)}), "
          f"placement groups: {status.get('num_pgs', 0)}")
    jobs = status.get("jobs", [])
    if jobs:
        print(f"driver jobs: {len(jobs)}")


def cmd_submit(args):
    from ray_tpu.jobs import JobSubmissionClient

    if not args.entrypoint:
        sys.exit("error: no entrypoint given — usage: "
                 "submit [opts] -- <command> [args...]")
    host, port = _resolve_address(args)
    client = JobSubmissionClient(f"{host}:{port}")
    entrypoint = " ".join(args.entrypoint)
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    sid = client.submit_job(entrypoint=entrypoint,
                            runtime_env=runtime_env or None)
    print(f"submitted: {sid}")
    if args.wait or args.follow:
        status = client.wait_until_finished(sid, timeout=args.timeout)
        if args.follow:
            sys.stdout.write(client.get_job_logs(sid))
        print(f"job {sid}: {status}")
        if status != "SUCCEEDED":
            sys.exit(1)


def cmd_jobs(args):
    from ray_tpu.jobs import JobSubmissionClient

    host, port = _resolve_address(args)
    client = JobSubmissionClient(f"{host}:{port}")
    if args.job_cmd == "list":
        for j in sorted(client.list_jobs(), key=lambda j: j.get("time", 0)):
            print(f"{j['submission_id']}  {j['status']:10s}  "
                  f"{j['entrypoint']}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        ok = client.stop_job(args.job_id)
        print("stopped" if ok else "not running")


def cmd_list(args):
    """State API listing (reference: `ray list ...`,
    util/state/state_cli.py)."""
    import json as _json

    import ray_tpu as ray
    from ray_tpu.util import state

    host, port = _resolve_address(args)
    ray.init(address=f"{host}:{port}")
    fn = {
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "workers": state.list_workers,
        "placement_groups": state.list_placement_groups,
        "jobs": state.list_jobs,
    }[args.entity]
    rows = fn()[: args.limit]
    if args.format == "json":
        print(_json.dumps(rows, indent=2, default=str))
        return
    if not rows:
        print(f"no {args.entity}")
        return
    cols = list(rows[0].keys())
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))[:40]) for r in rows))
        for c in cols
    }
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(
            str(r.get(c, ""))[:40].ljust(widths[c]) for c in cols))


def cmd_memory(args):
    """Object-store usage per node (reference: `ray memory`,
    scripts.py:2084)."""
    import ray_tpu as ray
    from ray_tpu.util import state

    host, port = _resolve_address(args)
    ray.init(address=f"{host}:{port}")
    objs = state.list_objects(limit=args.limit)
    by_node: dict = {}
    for o in objs:
        by_node.setdefault(o["node_id"], []).append(o)
    for nid, items in by_node.items():
        print(f"node {nid[:12]}: {len(items)} objects")
        for o in items:
            print(f"  {o['object_id']}")
    if not objs:
        print("no shm objects")


def cmd_events(args):
    """Structured export events for the session (reference:
    export_event_logger.py output)."""
    import json as _json

    from ray_tpu.util.events import read_events

    session_dir = args.session_dir
    if session_dir is None:
        cluster = _load_cluster()
        if cluster is None:
            print("no recorded cluster; pass --session-dir")
            return
        session_dir = cluster["session_dir"]
    for e in read_events(session_dir):
        print(_json.dumps(e))


def cmd_timeline(args):
    import ray_tpu as ray

    host, port = _resolve_address(args)
    ray.init(address=f"{host}:{port}")
    events = ray.timeline()
    with open(args.output, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {args.output}")


def cmd_microbenchmark(args):
    from ray_tpu.microbenchmark import main as bench_main

    bench_main()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray_tpu",
        description="ray_tpu cluster CLI (reference: ray start/stop/...)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or worker node")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", help="GCS address to join (worker nodes)")
    s.add_argument("--resources", help='JSON, e.g. \'{"CPU": 8}\'')
    s.add_argument("--dashboard-port", type=int, default=None,
                   help="serve the dashboard UI on this port (head only)")
    s.add_argument("--ray-client-server-port", type=int, default=None,
                   help="serve ray:// clients on this port (head only)")
    s.add_argument("--block", action="store_true",
                   help="stay attached; ctrl-c stops the node")
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("stop", help="stop the recorded local cluster")
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser("status", help="show cluster status")
    s.add_argument("--address")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("submit", help="submit a job entrypoint")
    s.add_argument("--address")
    s.add_argument("--working-dir")
    s.add_argument("--wait", action="store_true")
    s.add_argument("--follow", action="store_true",
                   help="wait and print the job log")
    s.add_argument("--timeout", type=float, default=600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="command to run, e.g. -- python train.py")
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("jobs", help="job management")
    s.add_argument("--address")
    jsub = s.add_subparsers(dest="job_cmd", required=True)
    jsub.add_parser("list")
    js = jsub.add_parser("status")
    js.add_argument("job_id")
    js = jsub.add_parser("logs")
    js.add_argument("job_id")
    js = jsub.add_parser("stop")
    js.add_argument("job_id")
    s.set_defaults(fn=cmd_jobs)

    s = sub.add_parser("list", help="list cluster entities (state API)")
    s.add_argument("entity", choices=[
        "actors", "tasks", "nodes", "objects", "workers",
        "placement_groups", "jobs"])
    s.add_argument("--address")
    s.add_argument("--format", choices=["table", "json"],
                   default="table")
    s.add_argument("--limit", type=int, default=100)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("memory", help="object store contents per node")
    s.add_argument("--address")
    s.add_argument("--limit", type=int, default=100)
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser("events", help="dump structured export events")
    s.add_argument("--session-dir", default=None)
    s.set_defaults(fn=cmd_events)

    s = sub.add_parser("timeline", help="export chrome-trace task events")
    s.add_argument("--address")
    s.add_argument("--output", default="timeline.json")
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser("microbenchmark",
                       help="run the core perf suite")
    s.set_defaults(fn=cmd_microbenchmark)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    # strip a leading "--" from REMAINDER entrypoints
    if getattr(args, "entrypoint", None) and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    args.fn(args)


if __name__ == "__main__":
    main()
