"""GKE / Cloud-TPU node provider: acquire TPU slices as cluster nodes.

Reference capability: the GCP provider
(python/ray/autoscaler/_private/gcp/node_provider.py:1 + node.py GCPTPU)
— create/terminate/list TPU VMs through Google REST APIs. Redesigned
TPU-first rather than translated:

- A *node type* here is a TPU slice shape: ``accelerator_type``
  (v5litepod-4, v5p-8, ...) + ``topology`` (2x2, 2x2x2, ...) +
  ``runtime_version``. Slices — not individual VMs — are the launch
  atom, because a pjit program needs every host of a slice (SURVEY §7
  "gang scheduling": sub-slice elasticity does not exist on TPU).
- Acquisition goes through the Cloud TPU **queued-resources** surface
  (``tpu.googleapis.com/v2`` ``queuedResources``), the API Google
  provisions modern slices with (guaranteed or spot), falling back to
  direct node creation (``nodes``) when ``use_queued_resources`` is
  off. On GKE the same shapes map to node pools with
  ``placementPolicy.tpuTopology``; the queued-resource path covers the
  TPU-VM architecture this framework targets first.
- A multi-host slice surfaces as ONE provider node whose
  ``host_count`` reflects the gang; the autoscaler counts its
  resources once per host via the node type's resources (which the
  scheduler fills with ``TPU`` chips + slice labels, matching the
  raylet's TPU detection labels: tpu-slice-name / tpu-topology /
  tpu-worker-id).

All HTTP goes through an injectable ``transport`` callable so unit
tests run against a mock (no cloud, no network — the repo's zero-egress
test policy). Auth: a bearer token from the transport owner
(``token_provider``), by default the GCE metadata server, matching how
the reference reaches ``tpu.googleapis.com`` from inside GCP.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from .config import AutoscalingConfig
from .node_provider import NodeProvider

TPU_API = "https://tpu.googleapis.com/v2"

# transport(method, url, body_dict_or_None, headers) -> (status, body_dict)
Transport = Callable[[str, str, Optional[dict], Dict[str, str]],
                     Tuple[int, dict]]


class GkeTpuError(RuntimeError):
    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = status


def _metadata_token() -> str:
    """Bearer token from the GCE metadata server (only reachable on
    GCP; tests inject token_provider instead)."""
    import urllib.request

    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())["access_token"]


def _urllib_transport(method: str, url: str, body: Optional[dict],
                      headers: Dict[str, str]) -> Tuple[int, dict]:
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
            return resp.status, (json.loads(payload) if payload else {})
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            parsed = json.loads(payload) if payload else {}
        except ValueError:
            parsed = {"raw": payload.decode(errors="replace")}
        return e.code, parsed


class GkeTpuNodeProvider(NodeProvider):
    """Launch TPU slices via the Cloud TPU queued-resources REST API.

    provider-specific node-type labels (set them in
    AutoscalingConfig.node_types[*].labels):
      tpu-accelerator-type: v5litepod-4 | v5p-8 | ...   (required)
      tpu-topology:         2x2 | 2x2x2 | ...           (optional)
      tpu-runtime-version:  runtime image               (optional)
      tpu-spot:             "1" for preemptible/spot capacity
    """

    def __init__(
        self,
        config: AutoscalingConfig,
        project: str,
        zone: str,
        cluster_name: str = "ray-tpu",
        *,
        use_queued_resources: bool = True,
        transport: Optional[Transport] = None,
        token_provider: Optional[Callable[[], str]] = None,
        poll_interval_s: float = 5.0,
    ):
        self.config = config
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.use_queued_resources = use_queued_resources
        self.transport = transport or _urllib_transport
        self.token_provider = token_provider or _metadata_token
        self.poll_interval_s = poll_interval_s
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        # provider_id -> {"node_type", "node_id", "state", "qr_name"}
        self._nodes: Dict[str, dict] = {}
        self._parent = f"projects/{project}/locations/{zone}"

    # ------------------------------------------------------------------
    # REST plumbing
    # ------------------------------------------------------------------
    def _call(self, method: str, path: str, body: Optional[dict] = None,
              *, retries: int = 3, ok_statuses: tuple = ()) -> dict:
        url = f"{TPU_API}/{path}" if not path.startswith("http") else path
        headers = {
            "Authorization": f"Bearer {self.token_provider()}",
            "Content-Type": "application/json",
        }
        backoff = 1.0
        for attempt in range(retries):
            status, payload = self.transport(method, url, body, headers)
            if status < 300 or status in ok_statuses:
                return payload
            if status in (429, 500, 502, 503) and attempt + 1 < retries:
                time.sleep(backoff)
                backoff *= 2
                continue
            raise GkeTpuError(
                f"{method} {url} -> {status}: "
                f"{payload.get('error', payload)}", status)
        raise GkeTpuError(f"{method} {url}: retries exhausted")

    # ------------------------------------------------------------------
    # NodeProvider surface
    # ------------------------------------------------------------------
    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        nt = self.config.node_types[node_type]
        accel = nt.labels.get("tpu-accelerator-type")
        if not accel:
            raise GkeTpuError(
                f"node type {node_type!r} has no tpu-accelerator-type "
                "label — GkeTpuNodeProvider launches TPU slices only")
        ids = []
        for _ in range(count):
            pid = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
            node_body = {
                "acceleratorType": accel,
                "runtimeVersion": nt.labels.get(
                    "tpu-runtime-version", "tpu-ubuntu2204-base"),
                "labels": {
                    "ray-cluster": self.cluster_name,
                    "ray-node-type": node_type,
                },
                "metadata": {
                    "ray-provider-id": pid,
                },
            }
            topo = nt.labels.get("tpu-topology")
            if topo:
                # explicit topology requests use acceleratorConfig —
                # the API rejects requests carrying BOTH acceleratorType
                # and acceleratorConfig, so the type moves inside it
                node_body.pop("acceleratorType")
                node_body["acceleratorConfig"] = {
                    "type": accel.split("-")[0].replace(
                        "v5litepod", "V5LITE_POD").upper(),
                    "topology": topo,
                }
            # 409 = this id already exists: a retried create whose
            # first attempt landed before a transient 5xx — success,
            # NOT an error (raising would leak the billable slice
            # untracked)
            if self.use_queued_resources:
                qr_name = pid
                body = {
                    "tpu": {"nodeSpec": [{
                        "parent": self._parent,
                        "nodeId": pid,
                        "node": node_body,
                    }]},
                }
                if nt.labels.get("tpu-spot") == "1":
                    body["spot"] = {}
                else:
                    body["guaranteed"] = {}
                self._call(
                    "POST",
                    f"{self._parent}/queuedResources"
                    f"?queuedResourceId={qr_name}",
                    body, ok_statuses=(409,),
                )
            else:
                qr_name = None
                self._call(
                    "POST", f"{self._parent}/nodes?nodeId={pid}",
                    node_body, ok_statuses=(409,),
                )
            with self._lock:
                self._nodes[pid] = {
                    "node_type": node_type,
                    "node_id": None,
                    "state": "CREATING",
                    "qr_name": qr_name,
                }
            ids.append(pid)
        return ids

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_id, None)
        if rec is None:
            return
        try:
            if rec.get("qr_name"):
                # deleting the queued resource releases the slice too
                # (force covers ACTIVE resources with a provisioned
                # node); 404 = already gone — that IS terminated
                self._call(
                    "DELETE",
                    f"{self._parent}/queuedResources/"
                    f"{rec['qr_name']}?force=true",
                    ok_statuses=(404,),
                )
            else:
                self._call("DELETE",
                           f"{self._parent}/nodes/{provider_id}",
                           ok_statuses=(404,))
        except GkeTpuError:
            # transient failure: re-track the node so the reconciler
            # retries the terminate
            with self._lock:
                self._nodes.setdefault(provider_id, rec)
            raise

    def non_terminated_nodes(self) -> Dict[str, dict]:
        self._refresh_states()
        # reap FAILED/SUSPENDED slices: hiding them without deleting
        # would leak the tracked record AND the cloud queued-resource
        # object against the project's quota
        with self._lock:
            dead = [pid for pid, r in self._nodes.items()
                    if r["state"] in ("FAILED", "SUSPENDED")]
        for pid in dead:
            try:
                self.terminate_node(pid)
            except GkeTpuError:
                pass  # retried on the next reconcile
        with self._lock:
            return {
                pid: {
                    "node_type": r["node_type"],
                    "node_id": r["node_id"],
                    "state": r["state"],
                }
                for pid, r in self._nodes.items()
                if r["state"] not in ("FAILED", "SUSPENDED")
            }

    # ------------------------------------------------------------------
    def _refresh_states(self):
        """One LIST call refreshes every tracked node's provisioning
        state (reference: cached DescribeInstances; per-node GETs would
        hammer the API at scale). Throttled by poll_interval_s — the
        reconciler calls non_terminated_nodes every loop tick."""
        now = time.monotonic()
        with self._lock:
            if not self._nodes:
                return
            if now - self._last_refresh < self.poll_interval_s:
                return
            self._last_refresh = now
            track_qr = any(r.get("qr_name") for r in self._nodes.values())
        states: Dict[str, str] = {}
        if track_qr:
            payload = self._call(
                "GET", f"{self._parent}/queuedResources")
            for qr in payload.get("queuedResources", []):
                name = qr.get("name", "").rsplit("/", 1)[-1]
                states[name] = qr.get("state", {}).get(
                    "state", "CREATING")
        payload = self._call("GET", f"{self._parent}/nodes")
        node_states: Dict[str, dict] = {}
        for node in payload.get("nodes", []):
            name = node.get("name", "").rsplit("/", 1)[-1]
            node_states[name] = node
        with self._lock:
            for pid, rec in self._nodes.items():
                qr = rec.get("qr_name")
                if qr and qr in states:
                    s = states[qr]
                    rec["state"] = {
                        "ACTIVE": "RUNNING",
                        "PROVISIONING": "CREATING",
                        "ACCEPTED": "CREATING",
                        "WAITING_FOR_RESOURCES": "CREATING",
                        "FAILED": "FAILED",
                        "SUSPENDED": "SUSPENDED",
                    }.get(s, "CREATING")
                node = node_states.get(pid)
                if node is not None:
                    if node.get("state") == "READY":
                        rec["state"] = "RUNNING"
                    # the raylet booting on the slice reports its node
                    # id through instance metadata the cluster launcher
                    # stamps; absent that, the autoscaler matches the
                    # node by its tpu-slice-name label at registration
                    rec["node_id"] = (
                        node.get("metadata", {}).get("ray-node-id")
                        or rec["node_id"])

    def shutdown(self) -> None:
        with self._lock:
            pids = list(self._nodes)
        for pid in pids:
            try:
                self.terminate_node(pid)
            except GkeTpuError:
                pass
