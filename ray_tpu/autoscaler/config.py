"""Autoscaling configuration schema.

Reference: the cluster-launcher YAML's ``available_node_types`` section
(python/ray/autoscaler/_private/util.py validates it) and
v2/instance_manager/config.py (NodeTypeConfig).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class NodeTypeConfig:
    """One launchable node shape (e.g. one TPU-host flavor)."""

    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 10

    def copy_resources(self) -> Dict[str, float]:
        return dict(self.resources)


@dataclass
class AutoscalingConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    max_workers: int = 64          # cluster-wide cap (excluding head)
    idle_timeout_s: float = 60.0   # terminate nodes idle this long
    update_interval_s: float = 1.0

    @staticmethod
    def from_dict(d: dict) -> "AutoscalingConfig":
        node_types = {
            name: NodeTypeConfig(
                name=name,
                resources=nt.get("resources", {}),
                labels=nt.get("labels", {}),
                min_workers=nt.get("min_workers", 0),
                max_workers=nt.get("max_workers", 10),
            )
            for name, nt in d.get("available_node_types", {}).items()
        }
        return AutoscalingConfig(
            node_types=node_types,
            max_workers=d.get("max_workers", 64),
            idle_timeout_s=d.get("idle_timeout_s", 60.0),
            update_interval_s=d.get("update_interval_s", 1.0),
        )
