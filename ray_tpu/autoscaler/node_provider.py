"""NodeProvider: the cloud-side plugin interface.

Reference: python/ray/autoscaler/node_provider.py:13 — the v1 ABC every
cloud implements (AWS/GCP/...); v2 wraps it in
instance_manager/cloud_providers/. Here the surface is the minimal
subset the reconciler needs; a GKE/GCE TPU provider implements it with
instance-group calls, tests use FakeNodeProvider.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional


class NodeProvider:
    """Launch/terminate/list cluster worker nodes.

    Implementations must be thread-safe: the autoscaler calls from its
    reconcile loop, tests may call concurrently.
    """

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        """Launch ``count`` nodes of ``node_type``; returns provider ids.

        May return before the node has joined the cluster — the
        autoscaler treats a created-but-not-yet-registered node as
        *pending* and avoids double-launching for the same demand.
        """
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, dict]:
        """provider_id -> {"node_type": str, "node_id": Optional[str]}.

        ``node_id`` is the cluster node id once the node has registered
        with the GCS (None while booting).
        """
        raise NotImplementedError

    def shutdown(self) -> None:
        pass
