"""Demand bin-packing: which nodes to launch for the pending work.

Reference: python/ray/autoscaler/v2/scheduler.py:638
(ResourceDemandScheduler) — bin-packs pending resource demand onto
hypothetical nodes of each configured type, respecting per-type and
cluster-wide caps. PG bundles are packed gang-style: all bundles of a
pending placement group must fit on the hypothetical fleet or none are
counted (a half-placed TPU slice gang is useless).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .._private.scheduling import resources_fit as _fits
from .._private.scheduling import subtract as _subtract
from .config import AutoscalingConfig, NodeTypeConfig


class ResourceDemandScheduler:
    def __init__(self, config: AutoscalingConfig):
        self.config = config

    def get_nodes_to_launch(
        self,
        pending_demand: List[Dict[str, float]],
        pending_pg_bundles: List[List[Dict[str, float]]],
        existing_avail: List[Dict[str, float]],
        counts_by_type: Dict[str, int],
    ) -> Dict[str, int]:
        """Returns {node_type: count} to launch.

        existing_avail: available resources of live + pending nodes (a
        booting node contributes its full node-type resources so demand
        already covered by an in-flight launch isn't double-served).
        counts_by_type: current per-type worker counts incl. pending.
        """
        # Hypothetical fleet = copies of existing availabilities we can
        # pack into, plus new nodes we decide to launch.
        fleet: List[Dict[str, float]] = [dict(a) for a in existing_avail]
        to_launch: Dict[str, int] = {}
        counts = dict(counts_by_type)
        total_workers = sum(counts.values())

        def try_pack(shape: Dict[str, float]) -> bool:
            nonlocal total_workers
            if not shape:
                return True
            for avail in fleet:
                if _fits(avail, shape):
                    _subtract(avail, shape)
                    return True
            # Need a new node: pick the cheapest type that fits (fewest
            # total resources — a stand-in for cost, deterministic).
            best: Optional[NodeTypeConfig] = None
            for nt in sorted(self.config.node_types.values(),
                             key=lambda t: (sum(t.resources.values()),
                                            t.name)):
                if not _fits(nt.copy_resources(), shape):
                    continue
                if counts.get(nt.name, 0) >= nt.max_workers:
                    continue
                if total_workers >= self.config.max_workers:
                    continue
                best = nt
                break
            if best is None:
                return False
            avail = best.copy_resources()
            _subtract(avail, shape)
            fleet.append(avail)
            to_launch[best.name] = to_launch.get(best.name, 0) + 1
            counts[best.name] = counts.get(best.name, 0) + 1
            total_workers += 1
            return True

        # min_workers floors first.
        for nt in self.config.node_types.values():
            deficit = nt.min_workers - counts.get(nt.name, 0)
            for _ in range(max(0, deficit)):
                if total_workers >= self.config.max_workers:
                    break
                fleet.append(nt.copy_resources())
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                counts[nt.name] = counts.get(nt.name, 0) + 1
                total_workers += 1

        # PG gangs: all-or-nothing (largest bundles first within a PG).
        for bundles in pending_pg_bundles:
            snapshot = ([dict(a) for a in fleet], dict(to_launch),
                        dict(counts), total_workers)
            ok = all(
                try_pack(b)
                for b in sorted(bundles,
                                key=lambda b: -sum(b.values()))
            )
            if not ok:
                fleet, to_launch, counts, total_workers = snapshot

        # Individual task/actor shapes, largest first (better packing).
        for shape in sorted(pending_demand, key=lambda s: -sum(s.values())):
            try_pack(shape)

        return to_launch

    def get_nodes_to_terminate(
        self,
        node_idle: Dict[str, Tuple[str, float]],
        counts_by_type: Dict[str, int],
    ) -> List[str]:
        """node_idle: provider_id -> (node_type, idle_duration_s).
        Terminates nodes idle past the timeout, never dropping a type
        below its min_workers."""
        out: List[str] = []
        counts = dict(counts_by_type)
        for pid, (ntype, idle_s) in sorted(
            node_idle.items(), key=lambda kv: -kv[1][1]
        ):
            if idle_s < self.config.idle_timeout_s:
                continue
            nt = self.config.node_types.get(ntype)
            floor = nt.min_workers if nt else 0
            if counts.get(ntype, 0) <= floor:
                continue
            out.append(pid)
            counts[ntype] = counts.get(ntype, 0) - 1
        return out
