"""Fake node provider: in-process "cloud" for tests and dev.

Reference: python/ray/autoscaler/_private/fake_multi_node/node_provider.py
— the provider behind nearly every autoscaler test in the reference CI
(test_autoscaler_fake_multinode.py). Here each launched node is a real
in-process raylet (ray_tpu Node) joined to the head's GCS, the same
mechanism cluster_utils.Cluster uses for multi-node simulation.
"""
from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional

from .config import AutoscalingConfig
from .node_provider import NodeProvider


class FakeNodeProvider(NodeProvider):
    def __init__(
        self,
        config: AutoscalingConfig,
        gcs_address,
        session_dir: Optional[str] = None,
        launch_delay_s: float = 0.0,
    ):
        self.config = config
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.launch_delay_s = launch_delay_s
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}  # provider_id -> record

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        nt = self.config.node_types[node_type]
        ids = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
            with self._lock:
                self._nodes[pid] = {"node_type": node_type, "node": None,
                                    "node_id": None}
            t = threading.Thread(
                target=self._boot, args=(pid, nt), daemon=True
            )
            t.start()
            ids.append(pid)
        return ids

    def _boot(self, pid: str, nt):
        import time

        from .._private.node import Node

        if self.launch_delay_s:
            time.sleep(self.launch_delay_s)
        node = Node(
            head=False,
            gcs_address=self.gcs_address,
            resources=dict(nt.resources),
            labels={**nt.labels, "node-type": nt.name},
            session_dir=self.session_dir,
        )
        with self._lock:
            rec = self._nodes.get(pid)
            if rec is None:  # terminated while booting
                node.shutdown()
                return
            rec["node"] = node
            rec["node_id"] = node.node_id

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_id, None)
        if rec and rec.get("node") is not None:
            rec["node"].shutdown()

    def non_terminated_nodes(self) -> Dict[str, dict]:
        with self._lock:
            return {
                pid: {"node_type": r["node_type"], "node_id": r["node_id"]}
                for pid, r in self._nodes.items()
            }

    def shutdown(self) -> None:
        with self._lock:
            recs = list(self._nodes.values())
            self._nodes.clear()
        for r in recs:
            if r.get("node") is not None:
                r["node"].shutdown()
