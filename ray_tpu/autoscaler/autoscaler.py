"""The autoscaler reconcile loop.

Reference: python/ray/autoscaler/v2/autoscaler.py:47 (Autoscaler,
update_autoscaling_state :169) + instance_manager/reconciler.py. One
iteration: read the GCS autoscaler state (pending demand + per-node
idle), diff against the provider's fleet, launch what the bin-packer
asks for, terminate idle nodes.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Tuple

from .config import AutoscalingConfig
from .node_provider import NodeProvider
from .scheduler import ResourceDemandScheduler

logger = logging.getLogger(__name__)


class Autoscaler:
    def __init__(
        self,
        config: AutoscalingConfig,
        provider: NodeProvider,
        gcs_client,
    ):
        self.config = config
        self.provider = provider
        self.gcs = gcs_client
        self.scheduler = ResourceDemandScheduler(config)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # pending-launch grace: provider_id -> launch ts (a created node
        # that never registers is abandoned after this long)
        self.launch_grace_s = 120.0
        self._launch_ts: Dict[str, float] = {}

    # -- one reconcile step (unit-testable without a loop) -------------
    def update(self) -> Tuple[Dict[str, int], list]:
        state = self.gcs.get_autoscaler_state()
        fleet = self.provider.non_terminated_nodes()
        gcs_nodes = state["nodes"]

        counts: Dict[str, int] = {}
        existing_avail = []
        node_idle: Dict[str, Tuple[str, float]] = {}
        now = time.time()
        for pid, rec in fleet.items():
            ntype = rec["node_type"]
            counts[ntype] = counts.get(ntype, 0) + 1
            nid = rec.get("node_id")
            info = gcs_nodes.get(nid) if nid else None
            if info is not None and info["alive"]:
                existing_avail.append(dict(info["available"]))
                node_idle[pid] = (ntype, info["idle_duration_s"])
                self._launch_ts.pop(pid, None)
            elif info is not None and not info["alive"]:
                # dead in GCS: reclaim the instance
                self.provider.terminate_node(pid)
                self._launch_ts.pop(pid, None)
                counts[ntype] -= 1
            else:
                # still booting: counts toward capacity with its full
                # node-type resources so we don't double-launch
                nt = self.config.node_types.get(ntype)
                if nt is not None:
                    existing_avail.append(nt.copy_resources())
                ts = self._launch_ts.setdefault(pid, now)
                if now - ts > self.launch_grace_s:
                    logger.warning("abandoning node %s (never joined)", pid)
                    self.provider.terminate_node(pid)
                    self._launch_ts.pop(pid, None)
                    counts[ntype] -= 1

        to_launch = self.scheduler.get_nodes_to_launch(
            state["pending_demand"],
            state["pending_pg_bundles"],
            existing_avail,
            counts,
        )
        for ntype, n in to_launch.items():
            for pid in self.provider.create_node(ntype, n):
                self._launch_ts[pid] = now

        to_kill = []
        if (
            not to_launch
            and not state["pending_demand"]
            and not state["pending_pg_bundles"]
        ):
            to_kill = self.scheduler.get_nodes_to_terminate(
                node_idle, counts
            )
            for pid in to_kill:
                nid = fleet[pid].get("node_id")
                if nid:
                    try:  # let running leases finish rejecting new work
                        self.gcs.drain_node(node_id=nid)
                    except Exception:
                        pass
                self.provider.terminate_node(pid)
        return to_launch, to_kill

    # -- background loop ----------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                logger.exception("autoscaler update failed")
            self._stop.wait(self.config.update_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# v1-compatible alias (reference: _private/autoscaler.py:172
# StandardAutoscaler — same loop, config-file driven)
StandardAutoscaler = Autoscaler
