"""Autoscaler: demand-driven cluster scaling.

Reference: python/ray/autoscaler/v2/autoscaler.py:47 (Autoscaler),
v2/scheduler.py:638 (ResourceDemandScheduler bin-packing),
v2/instance_manager/reconciler.py (instance state machine),
autoscaler/node_provider.py:13 (NodeProvider plugin ABC),
_private/gcp/node_provider.py:1 (cloud provider example).

TPU-native reframing: node types are *slices* — a node type carries the
resources and labels of one TPU host (or slice gang); the scheduler
bin-packs pending task/actor shapes and PG bundles onto hypothetical
nodes of each type, launches what's needed via the NodeProvider, and
terminates nodes idle past the timeout.
"""
from .config import AutoscalingConfig, NodeTypeConfig
from .node_provider import NodeProvider
from .fake_provider import FakeNodeProvider
from .gke_provider import GkeTpuNodeProvider
from .scheduler import ResourceDemandScheduler
from .autoscaler import Autoscaler, StandardAutoscaler


def make_provider(provider_config: dict, config: AutoscalingConfig,
                  **kwargs) -> NodeProvider:
    """Construct a provider from a cluster-config ``provider`` section
    (reference: the launcher YAML's ``provider.type`` dispatch,
    autoscaler/_private/providers.py)."""
    ptype = provider_config.get("type", "fake")
    if ptype in ("gke", "gcp-tpu", "tpu"):
        # forward only the kwargs this provider understands: generic
        # call sites also pass fake-provider plumbing (gcs_address,
        # session_dir) that must not reach the cloud provider
        gke_kw = {k: v for k, v in kwargs.items()
                  if k in ("transport", "token_provider",
                           "poll_interval_s")}
        return GkeTpuNodeProvider(
            config,
            project=provider_config["project_id"],
            zone=provider_config["availability_zone"],
            cluster_name=provider_config.get("cluster_name", "ray-tpu"),
            use_queued_resources=provider_config.get(
                "use_queued_resources", True),
            **gke_kw,
        )
    if ptype == "fake":
        return FakeNodeProvider(config, kwargs.get("gcs_address"),
                                session_dir=kwargs.get("session_dir"))
    raise ValueError(f"unknown provider type {ptype!r}")


__all__ = [
    "AutoscalingConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeNodeProvider",
    "GkeTpuNodeProvider",
    "ResourceDemandScheduler",
    "Autoscaler",
    "StandardAutoscaler",
    "make_provider",
]
