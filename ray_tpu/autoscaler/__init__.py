"""Autoscaler: demand-driven cluster scaling.

Reference: python/ray/autoscaler/v2/autoscaler.py:47 (Autoscaler),
v2/scheduler.py:638 (ResourceDemandScheduler bin-packing),
v2/instance_manager/reconciler.py (instance state machine),
autoscaler/node_provider.py:13 (NodeProvider plugin ABC).

TPU-native reframing: node types are *slices* — a node type carries the
resources and labels of one TPU host (or slice gang); the scheduler
bin-packs pending task/actor shapes and PG bundles onto hypothetical
nodes of each type, launches what's needed via the NodeProvider, and
terminates nodes idle past the timeout.
"""
from .config import AutoscalingConfig, NodeTypeConfig
from .node_provider import NodeProvider
from .fake_provider import FakeNodeProvider
from .scheduler import ResourceDemandScheduler
from .autoscaler import Autoscaler, StandardAutoscaler

__all__ = [
    "AutoscalingConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeNodeProvider",
    "ResourceDemandScheduler",
    "Autoscaler",
    "StandardAutoscaler",
]
