"""ray.util.collective-compatible surface.

Reference: python/ray/util/collective/collective.py — GroupManager
(:60), init_collective_group (:150), allreduce (:295) with NCCL/Gloo
backends. Here the DEVICE plane is jax collectives inside pjit/shard_map
programs (parallel/collectives.py — allreduce/allgather/all_to_all as
`lax` wrappers over mesh axes), so this module provides the HOST-plane
group API with the reference's names: named groups, barrier, and
object/array collectives over the GCS KV rendezvous (the Gloo-analogue
control plane; reference: gloo_collective_group.py).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ..parallel.collectives import HostCollectiveGroup

_groups: Dict[str, HostCollectiveGroup] = {}


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Reference: collective.py:150 — every participant calls this with
    its rank before using the group."""
    if backend not in ("host", "gloo", "cpu"):
        raise ValueError(
            f"backend {backend!r} not supported: device-plane "
            "collectives are jax ops inside pjit programs "
            "(ray_tpu.parallel.collectives); host groups use 'host'")
    _groups[group_name] = HostCollectiveGroup(
        group_name, world_size=world_size, rank=rank)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None:
        g.teardown()


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def barrier(group_name: str = "default", timeout: float = 120.0) -> None:
    _groups[group_name].barrier(timeout=timeout)


def allreduce(tensor: np.ndarray, group_name: str = "default",
              op: str = "sum", timeout: float = 120.0) -> np.ndarray:
    """Array allreduce through the host plane; returns the reduced
    array (the reference mutates in place — numpy arrays here are
    copied on gather, so the result is returned AND written back when
    the input is writable)."""
    g = _groups[group_name]
    parts = g.allgather_obj(np.asarray(tensor), timeout=timeout)
    stacked = np.stack(parts)
    if op == "sum":
        out = stacked.sum(axis=0)
    elif op == "max":
        out = stacked.max(axis=0)
    elif op == "min":
        out = stacked.min(axis=0)
    elif op in ("mean", "avg"):
        out = stacked.mean(axis=0)
    else:
        raise ValueError(f"unknown reduce op {op!r}")
    try:
        tensor[...] = out
    except (TypeError, ValueError):
        pass
    return out


def allgather(tensor: np.ndarray, group_name: str = "default",
              timeout: float = 120.0) -> list:
    return _groups[group_name].allgather_obj(
        np.asarray(tensor), timeout=timeout)


def broadcast(tensor: Any, src_rank: int = 0,
              group_name: str = "default",
              timeout: float = 120.0) -> Any:
    g = _groups[group_name]
    value = tensor if g.rank == src_rank else None
    return g.broadcast_obj(value, root=src_rank, timeout=timeout)


def reduce(tensor: np.ndarray, dst_rank: int = 0,
           group_name: str = "default", op: str = "sum",
           timeout: float = 120.0) -> Optional[np.ndarray]:
    out = allreduce(tensor, group_name, op, timeout)
    return out if _groups[group_name].rank == dst_rank else None
