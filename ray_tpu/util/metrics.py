"""User-facing metrics API: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (-> includes/metric.pxi -> C++
stats). Here metrics record into the process-local registry
(_private/metrics.py); the worker's flush loop ships snapshots to its
raylet, which serves the node-wide Prometheus scrape on
http://<node>:<metrics_port>/metrics.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from .._private.metrics import get_registry


class Counter:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._impl = get_registry().counter(name, description)
        self._tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._tags = dict(tags)
        return self

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        self._impl.inc(value, {**self._tags, **(tags or {})})


class Gauge:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._impl = get_registry().gauge(name, description)
        self._tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._tags = dict(tags)
        return self

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._impl.set(value, {**self._tags, **(tags or {})})


class Histogram:
    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = ()):
        from .._private.metrics import _DEFAULT_BUCKETS

        self._impl = get_registry().histogram(
            name, description, tuple(boundaries) or _DEFAULT_BUCKETS
        )
        self._tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._tags = dict(tags)
        return self

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        self._impl.observe(value, {**self._tags, **(tags or {})})
