"""Task tracing: span propagation across task/actor boundaries.

Reference: python/ray/util/tracing/tracing_helper.py —
`_tracing_task_invocation` / `_inject_tracing_into_function` (:293,:326)
wrap submission and execution, propagating otel span context inside task
specs; `ray timeline` exports Chrome-trace JSON.

Here spans are framework-native (no otel in the image): a contextvar
carries (trace_id, span_id); submission stamps it into the task spec;
execution opens a child span and records it to the GCS task-event store,
where ``ray_tpu.timeline()`` / the dashboard render Chrome-trace
complete ("X") events with parent links.
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import time
import uuid
from typing import Any, Dict, Optional

_ctx: contextvars.ContextVar[Optional[Dict[str, str]]] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)


def tracing_enabled() -> bool:
    return os.environ.get("RAY_TPU_TRACING_ENABLED", "0") == "1"


def current_context() -> Optional[Dict[str, str]]:
    return _ctx.get()


def context_for_spec() -> Optional[Dict[str, str]]:
    """Called at submission: the ctx stamped into the task spec."""
    if not tracing_enabled():
        return None
    ctx = _ctx.get()
    if ctx is None:
        # root: the driver's first traced submission opens a trace
        ctx = {"trace_id": uuid.uuid4().hex, "span_id": "root"}
        _ctx.set(ctx)
    return dict(ctx)


def stamp_spec(spec: dict) -> None:
    """Submission-side: stamp the current trace context into a task
    spec (no-op when tracing is disabled)."""
    ctx = context_for_spec()
    if ctx:
        spec["trace_ctx"] = ctx


@contextlib.contextmanager
def task_span(spec: dict, worker):
    """Execution-side: open a span for a task spec, or no-op when the
    spec carries no trace context."""
    if not spec.get("trace_ctx"):
        yield None
        return
    with span(spec.get("name", "task"), worker=worker, spec=spec) as s:
        yield s


@contextlib.contextmanager
def span(name: str, worker=None, spec: Optional[dict] = None):
    """Execution-side (or user-code) span. Records a complete event to
    the worker's task-event buffer on exit."""
    parent = None
    if spec is not None and spec.get("trace_ctx"):
        parent = dict(spec["trace_ctx"])
        token = _ctx.set(parent)
    else:
        cur = _ctx.get()
        parent = dict(cur) if cur else None
        token = None
    sid = uuid.uuid4().hex[:16]
    mine = {
        "trace_id": (parent or {}).get("trace_id", uuid.uuid4().hex),
        "span_id": sid,
    }
    inner_token = _ctx.set(mine)
    start = time.time()
    try:
        yield mine
    finally:
        end = time.time()
        _ctx.reset(inner_token)
        if token is not None:
            _ctx.reset(token)
        if worker is not None and tracing_enabled():
            with worker._task_events_lock:
                worker._task_events.append({
                    "task_id": (spec or {}).get("task_id", b"").hex()
                    if isinstance((spec or {}).get("task_id"), bytes)
                    else (spec or {}).get("task_id", ""),
                    "name": name,
                    "state": "SPAN",
                    "ts": start,
                    "dur": end - start,
                    "trace_id": mine["trace_id"],
                    "span_id": sid,
                    "parent_span_id": (parent or {}).get("span_id"),
                    "node_id": worker.node_id,
                    "job_id": (spec or {}).get("job_id"),
                })


def spans_to_chrome_trace(events) -> list:
    """SPAN task events -> Chrome-trace 'X' (complete) slices."""
    out = []
    for e in events:
        if e.get("state") != "SPAN":
            continue
        out.append({
            "name": e.get("name", ""),
            "cat": "task",
            "ph": "X",
            "ts": e["ts"] * 1e6,
            "dur": e.get("dur", 0.0) * 1e6,
            "pid": e.get("node_id", ""),
            "tid": e.get("trace_id", ""),
            "args": {
                "span_id": e.get("span_id"),
                "parent_span_id": e.get("parent_span_id"),
                "task_id": e.get("task_id"),
            },
        })
    return out
