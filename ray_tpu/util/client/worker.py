"""Client-side worker: the ``ray://`` driver surface.

Reference: python/ray/util/client/worker.py:81 (Worker — owns the gRPC
channel, mirrors put/get/wait/remote/actor calls through the server)
and api.py (ClientAPI). Stub classes here mirror the real
RemoteFunction/ActorClass/ActorHandle surface closely enough that
driver scripts run unchanged against either mode.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

import cloudpickle

from ..._private import serialization
from ..._private.rpc import RpcClient
from .common import client_dumps, dumps_definition


class ClientObjectRef:
    __slots__ = ("id", "_worker")

    def __init__(self, id_hex: str, worker: "ClientWorker"):
        self.id = id_hex
        self._worker = worker

    def __repr__(self):
        return f"ClientObjectRef({self.id[:16]})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return (isinstance(other, ClientObjectRef)
                and other.id == self.id)

    def __reduce__(self):
        raise TypeError(
            "ClientObjectRef cannot be pickled outside client calls")

    def __del__(self):
        w = self._worker
        if w is not None and not getattr(w, "_closed", True):
            try:
                w._mark_released(self.id)
            except Exception:
                pass


class ClientRemoteMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name
        self._num_returns: Optional[int] = None

    def options(self, num_returns: Optional[int] = None):
        m = ClientRemoteMethod(self._handle, self._name)
        m._num_returns = num_returns
        return m

    def remote(self, *args, **kwargs):
        w = self._handle._worker
        n = self._num_returns if self._num_returns is not None else 1
        if not isinstance(n, int) or n < 1:
            # streaming / exotic returns: plain round-trip
            ids = w._call(
                "client_actor_task",
                actor_id=self._handle.actor_id,
                method_name=self._name,
                args_blob=client_dumps((args, kwargs)),
                num_returns=self._num_returns,
            )
            refs = [ClientObjectRef(i, w) for i in ids]
            return refs[0] if len(refs) == 1 else refs
        # pipelined: client assigns the rids, the submission rides the
        # next batched flush (see ClientWorker._flush_tasks)
        refs = w._queue_task({
            "kind": "actor_task",
            "actor_id": self._handle.actor_id,
            "method_name": self._name,
            "args_blob": client_dumps((args, kwargs)),
            "num_returns": self._num_returns,
        }, n)
        return refs[0] if len(refs) == 1 else refs


class ClientActorHandle:
    def __init__(self, actor_id: str, worker: "ClientWorker"):
        self.actor_id = actor_id
        self._worker = worker

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientRemoteMethod(self, name)

    def __repr__(self):
        return f"ClientActorHandle({self.actor_id[:16]})"


class ClientRemoteFunction:
    """The function body + base options ship once per session
    (reference: the client function cache); per-call .options()
    overrides ride each task RPC."""

    def __init__(self, fn, worker: "ClientWorker", options: dict):
        self._fn = fn
        self._worker = worker
        self._base_options = dict(options)
        self._func_id = f"f-{uuid.uuid4().hex[:12]}"
        self._registered = False
        self._call_options: Optional[dict] = None

    def options(self, **overrides) -> "ClientRemoteFunction":
        out = ClientRemoteFunction.__new__(ClientRemoteFunction)
        out.__dict__.update(self.__dict__)
        out._call_options = overrides
        return out

    def remote(self, *args, **kwargs):
        w = self._worker
        if not self._registered:
            w._call(
                "client_register_function",
                func_id=self._func_id,
                blob=dumps_definition(self._fn),
                options=self._base_options,
            )
            self._registered = True
        n = 1
        for opts in (self._base_options, self._call_options or {}):
            n = opts.get("num_returns", n)
        if not isinstance(n, int) or n < 1:
            # streaming / exotic returns: plain round-trip
            ids = w._call(
                "client_task",
                func_id=self._func_id,
                args_blob=client_dumps((args, kwargs)),
                options=self._call_options,
            )
            refs = [ClientObjectRef(i, w) for i in ids]
            return refs[0] if len(refs) == 1 else refs
        refs = w._queue_task({
            "kind": "task",
            "func_id": self._func_id,
            "args_blob": client_dumps((args, kwargs)),
            "options": self._call_options,
        }, n)
        return refs[0] if len(refs) == 1 else refs


class ClientActorClass:
    def __init__(self, cls, worker: "ClientWorker", options: dict):
        self._cls = cls
        self._worker = worker
        self._options = dict(options)
        self._class_id = f"c-{uuid.uuid4().hex[:12]}"
        self._registered = False
        self._call_options: Optional[dict] = None

    def options(self, **overrides) -> "ClientActorClass":
        out = ClientActorClass.__new__(ClientActorClass)
        out.__dict__.update(self.__dict__)
        out._call_options = overrides
        return out

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        w = self._worker
        if not self._registered:
            w._call(
                "client_register_actor_class",
                class_id=self._class_id,
                blob=dumps_definition(self._cls),
                options=self._options,
            )
            self._registered = True
        info = w._call(
            "client_create_actor",
            class_id=self._class_id,
            args_blob=client_dumps((args, kwargs)),
            options=self._call_options,
        )
        return ClientActorHandle(info["actor_id"], w)


class ClientWorker:
    """One connection to a ClientServer; the client-mode 'global
    worker'."""

    def __init__(self, host: str, port: int, namespace: str = ""):
        self._client = RpcClient(host, port)
        self._lock = threading.Lock()
        self._released: List[str] = []
        self._pending_tasks: List[dict] = []
        self._flush_timer_armed = False
        self._send_lock = threading.Lock()
        self._closed = False
        res = self._call("client_connect", _no_session=True,
                         namespace=namespace)
        self.session_id = res["session_id"]
        self.namespace = namespace
        # liveness heartbeat: lets the server reap sessions whose client
        # died without disconnect() (reference: client keepalive stream)
        self._hb = threading.Thread(target=self._heartbeat, daemon=True)
        self._hb.start()

    def _heartbeat(self):
        while not self._closed:
            time.sleep(15.0)
            if self._closed:
                return
            try:
                self._client.call_sync(
                    "client_ping", timeout=30.0,
                    session_id=self.session_id)
            except Exception:
                pass

    # Mutating ops must not be replayed after a mid-call connection
    # drop (same convention as core_worker's push paths).
    _NON_IDEMPOTENT = frozenset({
        "client_put", "client_task", "client_actor_task",
        "client_create_actor",
    })
    # Ops that legitimately block as long as the cluster needs.
    _UNTIMED = frozenset({"client_get", "client_wait"})

    def _call(self, method: str, _no_session: bool = False, **kwargs):
        if not _no_session:
            kwargs["session_id"] = self.session_id
        # pipelined submissions must land before any dependent op (and
        # before releases: a submission binds rids a release might name)
        self._flush_tasks()
        self._flush_released()
        return self._client.call_sync(
            method,
            timeout=None if method in self._UNTIMED else 300.0,
            idempotent=method not in self._NON_IDEMPOTENT,
            **kwargs,
        )

    # -- pipelined task submission -------------------------------------
    def _queue_task(self, item: dict, num_returns: int):
        """Assign rids client-side and queue the submission; ONE
        client_tasks_batch RPC carries the whole burst (reference: the
        client datapath stream pipelines task ops). A 5 ms timer flushes
        fire-and-forget submissions that no later RPC would carry."""
        rids = [f"r-{uuid.uuid4().hex}" for _ in range(max(1, num_returns))]
        item["ref_ids"] = rids
        arm = False
        with self._lock:
            self._pending_tasks.append(item)
            n = len(self._pending_tasks)
            if not self._flush_timer_armed:
                self._flush_timer_armed = arm = True
        if n >= 200:
            self._flush_tasks()
        elif arm:
            t = threading.Timer(0.005, self._timer_flush)
            t.daemon = True
            t.start()
        return [ClientObjectRef(i, self) for i in rids]

    def _timer_flush(self):
        with self._lock:
            self._flush_timer_armed = False
        try:
            self._flush_tasks()
        except Exception:
            # batch was re-queued by _flush_tasks; retry on a backoff
            # timer so fire-and-forget submissions still eventually land
            with self._lock:
                if self._flush_timer_armed or self._closed:
                    return
                self._flush_timer_armed = True
            t = threading.Timer(0.2, self._timer_flush)
            t.daemon = True
            t.start()

    def _flush_tasks(self):
        # _send_lock serializes swap+send: a dependent RPC entering
        # _call blocks here until the in-flight batch has actually
        # reached the server, so client_get can never overtake the
        # submission that binds its rid
        with self._send_lock:
            with self._lock:
                if not self._pending_tasks:
                    return
                batch, self._pending_tasks = self._pending_tasks, []
            try:
                self._client.call_sync(
                    "client_tasks_batch", timeout=300.0, idempotent=False,
                    session_id=self.session_id, items=batch,
                )
            except Exception:
                # put the batch back (order preserved) — the next _call
                # or backoff timer retries; a permanently dead server
                # fails the caller's own RPC instead
                with self._lock:
                    self._pending_tasks[:0] = batch
                raise

    # -- ref lifetime -------------------------------------------------
    def _mark_released(self, ref_id: str):
        with self._lock:
            self._released.append(ref_id)

    def _flush_released(self):
        with self._lock:
            if not self._released:
                return
            batch, self._released = self._released, []
        try:
            self._client.call_sync(
                "client_release", timeout=60.0,
                session_id=self.session_id, ref_ids=batch)
        except Exception:
            pass

    # -- API surface --------------------------------------------------
    def put(self, value: Any) -> ClientObjectRef:
        rid = self._call("client_put",
                         payload=serialization.dumps(value))
        return ClientObjectRef(rid, self)

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]],
            timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        lst = [refs] if single else list(refs)
        payload = self._call("client_get",
                             ref_ids=[r.id for r in lst],
                             get_timeout=timeout)
        values = serialization.loads(payload)
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        by_id = {r.id: r for r in refs}
        res = self._call("client_wait", ref_ids=list(by_id),
                         num_returns=num_returns, wait_timeout=timeout)
        return ([by_id[i] for i in res["ready"]],
                [by_id[i] for i in res["pending"]])

    def remote(self, obj, **options):
        if isinstance(obj, type):
            return ClientActorClass(obj, self, options)
        return ClientRemoteFunction(obj, self, options)

    def get_actor(self, name: str, namespace: str = ""
                  ) -> ClientActorHandle:
        info = self._call("client_get_actor", name=name,
                          namespace=namespace)
        return ClientActorHandle(info["actor_id"], self)

    def kill(self, actor: ClientActorHandle, no_restart: bool = True):
        self._call("client_kill_actor", actor_id=actor.actor_id,
                   no_restart=no_restart)

    def api(self, api_method: str):
        return self._call("client_api", api_method=api_method)

    def disconnect(self):
        self._closed = True
        try:
            self._call("client_disconnect")
        except Exception:
            pass
        self._client.close_sync()
