"""Wire helpers: pickling with object-ref indirection.

Reference: python/ray/util/client/client_pickler.py — client pickles
args with ClientObjectRef/ClientActorHandle reduced to id stubs; the
server unpickles stubs back into real ObjectRefs/handles. Implemented
with pickle's persistent_id/persistent_load hooks so refs nested
anywhere in an argument tree round-trip without a manual walk.
"""
from __future__ import annotations

import io
import pickle
from typing import Any, Callable

import cloudpickle


def _is_client_local(obj) -> bool:
    """True for classes/functions defined in modules that exist only on
    the client machine (not stdlib, not installed packages): those must
    pickle BY VALUE or the server fails with ModuleNotFoundError."""
    import sys
    import sysconfig

    mod_name = getattr(obj, "__module__", "") or ""
    if mod_name in ("builtins", "__main__") or \
            mod_name.split(".")[0] in ("ray_tpu", "numpy", "jax"):
        return mod_name == "__main__"
    mod = sys.modules.get(mod_name)
    f = getattr(mod, "__file__", None) if mod else None
    if f is None:
        return False  # builtin/extension module: importable everywhere
    stdlib = sysconfig.get_paths()["stdlib"]
    return not (f.startswith(stdlib) or "site-packages" in f
                or "dist-packages" in f)


class ClientPickler(cloudpickle.CloudPickler):
    """Replaces client-side stubs with ("ref"|"actor", id) pids and
    pickles client-local classes by value (an argument's CLASS is
    normally stored as a module reference)."""

    def persistent_id(self, obj):
        from .worker import ClientActorHandle, ClientObjectRef

        if isinstance(obj, ClientObjectRef):
            return ("ref", obj.id)
        if isinstance(obj, ClientActorHandle):
            return ("actor", obj.actor_id)
        return None

    def reducer_override(self, obj):
        import types

        if isinstance(obj, type) and _is_client_local(obj):
            try:
                return cloudpickle.cloudpickle._dynamic_class_reduce(obj)
            except Exception:
                pass
        if isinstance(obj, types.FunctionType) and _is_client_local(obj):
            try:
                return self._dynamic_function_reduce(obj)
            except Exception:
                pass
        return super().reducer_override(obj)


def client_dumps(obj: Any) -> bytes:
    buf = io.BytesIO()
    ClientPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def dumps_definition(obj: Any) -> bytes:
    """Pickle a function/class BY VALUE: the client's modules are not
    importable on the cluster (the whole point of ray://), so
    module-level definitions must ship their code, not a module path
    (reference: client_pickler registers the driver's modules for
    by-value pickling)."""
    import sys

    mod = sys.modules.get(getattr(obj, "__module__", ""), None)
    name = getattr(mod, "__name__", "")
    if mod is None or name in ("builtins", "__main__") or \
            name.startswith("ray_tpu"):
        return cloudpickle.dumps(obj)
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception:
        return cloudpickle.dumps(obj)
    try:
        return cloudpickle.dumps(obj)
    finally:
        try:
            cloudpickle.unregister_pickle_by_value(mod)
        except Exception:
            pass


class ServerUnpickler(pickle.Unpickler):
    def __init__(self, data: bytes, resolve: Callable[[str, str], Any]):
        super().__init__(io.BytesIO(data))
        self._resolve = resolve

    def persistent_load(self, pid):
        kind, ident = pid
        return self._resolve(kind, ident)


def server_loads(data: bytes, resolve: Callable[[str, str], Any]) -> Any:
    return ServerUnpickler(data, resolve).load()
