"""Client server: hosts per-client proxy state on the cluster side.

Reference: python/ray/util/client/server/server.py (RayletServicer —
per-client object/actor registries, function cache, data streaming) +
proxier.py. Runs inside any cluster-connected process (typically the
head node, started by `ray-tpu start --head --ray-client-server-port`).

Every RPC executes through the PUBLIC driver API of this process (put/
get/wait/remote) — the server is a consumer of the framework, not a
backdoor, mirroring how the reference's specific server drives
ray.* on behalf of the client.
"""
from __future__ import annotations

import asyncio
import threading
import uuid
from typing import Any, Dict, Optional

import cloudpickle

from ..._private import serialization
from ..._private.rpc import EventLoopThread, RpcServer
from .common import server_loads


_DYNAMIC_MODULE = "ray_tpu.util.client.__dynamic__"  # not importable


def _mark_dynamic(obj) -> None:
    """Detach a client-shipped definition from any module path.

    The server process may coincidentally import a module with the same
    name as the client's (e.g. both run the same script); cloudpickle
    would then re-pickle the definition — and, for classes, every
    METHOD — BY REFERENCE when shipping it to workers, and workers,
    which lack the module, fail with ModuleNotFoundError. Pointing
    __module__ at a non-importable name forces by-value pickling
    everywhere downstream."""
    import types

    try:
        obj.__module__ = _DYNAMIC_MODULE
    except Exception:
        pass
    if isinstance(obj, type):
        for attr in vars(obj).values():
            fn = attr
            if isinstance(attr, (staticmethod, classmethod)):
                fn = attr.__func__
            elif isinstance(attr, property):
                for f in (attr.fget, attr.fset, attr.fdel):
                    if isinstance(f, types.FunctionType):
                        _mark_dynamic(f)
                continue
            if isinstance(fn, types.FunctionType):
                try:
                    fn.__module__ = _DYNAMIC_MODULE
                except Exception:
                    pass


class _Session:
    def __init__(self, namespace: str):
        import time

        self.namespace = namespace
        self.refs: Dict[str, Any] = {}        # ref id hex -> ObjectRef
        self.actors: Dict[str, Any] = {}      # actor id -> ActorHandle
        self.funcs: Dict[str, Any] = {}       # func id -> RemoteFunction
        self.actor_classes: Dict[str, Any] = {}
        self.last_seen = time.time()


class ClientServer:
    # a session whose client hasn't been heard from (clients heartbeat
    # every 15s) is reaped, releasing its pinned refs/handles
    SESSION_TTL_S = 120.0

    def __init__(self, host: str = "127.0.0.1", port: int = 10001):
        from concurrent.futures import ThreadPoolExecutor

        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        # dedicated pool: untimed client_get/client_wait calls park a
        # thread each until their ref resolves — on the loop's default
        # (cpu-sized) executor a handful of slow gets would starve every
        # other RPC for every session
        self._executor = ThreadPoolExecutor(
            max_workers=128, thread_name_prefix="client-server")
        self._server = RpcServer(host, port)
        self._server.register(self)  # methods are already client_*-named
        loop = EventLoopThread.get().loop
        asyncio.run_coroutine_threadsafe(
            self._server.start(), loop).result(15)
        self.address = self._server.address
        self._reaper = asyncio.run_coroutine_threadsafe(
            self._reap_loop(), loop)

    def stop(self):
        loop = EventLoopThread.get().loop
        self._reaper.cancel()
        asyncio.run_coroutine_threadsafe(
            self._server.stop(), loop).result(10)

    async def _reap_loop(self):
        import time

        while True:
            await asyncio.sleep(self.SESSION_TTL_S / 4)
            cutoff = time.time() - self.SESSION_TTL_S
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if s.last_seen < cutoff]
                for sid in dead:
                    self._sessions.pop(sid, None)

    # -- helpers -------------------------------------------------------
    def _session(self, session_id: str) -> _Session:
        import time

        s = self._sessions.get(session_id)
        if s is None:
            raise KeyError(f"unknown client session {session_id}")
        s.last_seen = time.time()
        return s

    def _resolve(self, sess: _Session, kind: str, ident: str):
        if kind == "ref":
            return sess.refs[ident]
        if kind == "actor":
            return sess.actors[ident]
        raise KeyError(kind)

    def _load_args(self, sess: _Session, blob: bytes):
        args, kwargs = server_loads(
            blob, lambda k, i: self._resolve(sess, k, i))
        return args, kwargs

    def _track(self, sess: _Session, refs) -> list:
        out = []
        for r in refs if isinstance(refs, (list, tuple)) else [refs]:
            sess.refs[r.id.hex()] = r
            out.append(r.id.hex())
        return out

    # -- RPC surface (async handlers on the shared loop; blocking API
    #    calls hop to a thread so the loop never stalls) ---------------
    async def _in_thread(self, fn, *args, **kw):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, lambda: fn(*args, **kw))

    async def client_connect(self, namespace: str = "") -> dict:
        session_id = uuid.uuid4().hex
        with self._lock:
            self._sessions[session_id] = _Session(namespace)
        return {"session_id": session_id}

    async def client_disconnect(self, session_id: str) -> bool:
        with self._lock:
            self._sessions.pop(session_id, None)
        return True

    async def client_put(self, session_id: str, payload: bytes) -> str:
        import ray_tpu as ray

        sess = self._session(session_id)
        value = serialization.loads(payload)
        ref = await self._in_thread(ray.put, value)
        return self._track(sess, ref)[0]

    async def client_get(self, session_id: str, ref_ids: list,
                         get_timeout: Optional[float] = None) -> bytes:
        import ray_tpu as ray

        sess = self._session(session_id)
        refs = [sess.refs[i] for i in ref_ids]
        for r in refs:
            # a pipelined submission that failed server-side parks its
            # exception under the client-assigned rid (client_tasks_batch)
            if isinstance(r, Exception):
                raise r
        values = await self._in_thread(
            ray.get, refs, timeout=get_timeout)
        return serialization.dumps(values)

    async def client_tasks_batch(self, session_id: str,
                                 items: list) -> bool:
        """Pipelined task submissions: the client pre-assigned the ref
        ids, so ONE RPC carries many .remote() calls and needs no
        per-call reply (reference: the Ray Client datapath pipelines
        task ops on its gRPC stream instead of round-tripping each;
        python/ray/util/client/dataclient.py). Submission errors are
        parked under the assigned rid and re-raised by client_get.

        The RPC is retried by the client after connection loss, and the
        lost batch may already have executed here — items whose first
        ref id is already bound are SKIPPED, so a retry never submits a
        task twice (client-assigned rids double as dedup keys)."""
        sess = self._session(session_id)

        def submit_all():
            for it in items:
                rids = it["ref_ids"]
                if rids and rids[0] in sess.refs:
                    continue  # duplicate delivery of an applied item
                try:
                    args, kwargs = self._load_args(sess, it["args_blob"])
                    if it["kind"] == "task":
                        fn = sess.funcs[it["func_id"]]
                        if it.get("options"):
                            fn = fn.options(**it["options"])
                        refs = fn.remote(*args, **kwargs)
                    else:
                        m = getattr(sess.actors[it["actor_id"]],
                                    it["method_name"])
                        if it.get("num_returns") is not None:
                            m = m.options(num_returns=it["num_returns"])
                        refs = m.remote(*args, **kwargs)
                    if not isinstance(refs, (list, tuple)):
                        refs = [refs]
                    for rid, ref in zip(rids, refs):
                        sess.refs[rid] = ref
                except Exception as e:  # noqa: BLE001 — parked per-rid
                    for rid in rids:
                        sess.refs[rid] = e

        await self._in_thread(submit_all)
        return True

    async def client_wait(self, session_id: str, ref_ids: list,
                          num_returns: int = 1,
                          wait_timeout: Optional[float] = None) -> dict:
        import ray_tpu as ray

        sess = self._session(session_id)
        # failed pipelined submissions count as 'ready' (their get
        # raises — matching ray.wait semantics for errored refs), but
        # the reply still honors len(ready) == num_returns
        failed = [i for i in ref_ids
                  if isinstance(sess.refs.get(i), Exception)]
        live_ids = [i for i in ref_ids
                    if not isinstance(sess.refs.get(i), Exception)]
        need = max(0, min(num_returns, len(ref_ids)) - len(failed))
        ready_ids: list = []
        if need and live_ids:
            # dedupe instances for the ray.wait call; readiness then
            # applies to every rid aliasing a ready instance
            uniq = list({id(sess.refs[i]): sess.refs[i]
                         for i in live_ids}.values())
            ready, _pending = await self._in_thread(
                ray.wait, uniq, num_returns=min(need, len(uniq)),
                timeout=wait_timeout)
            ready_set = {id(r) for r in ready}
            ready_ids = [i for i in live_ids
                         if id(sess.refs[i]) in ready_set]
        out_ready = (failed + ready_ids)[:num_returns]
        taken = set(out_ready)
        pending_ids = [i for i in ref_ids if i not in taken]
        return {"ready": out_ready, "pending": pending_ids}

    async def client_release(self, session_id: str, ref_ids: list) -> bool:
        sess = self._session(session_id)
        for i in ref_ids:
            sess.refs.pop(i, None)
        return True

    async def client_register_function(self, session_id: str,
                                       func_id: str, blob: bytes,
                                       options: dict) -> bool:
        """Function shipped once per session (reference: the client's
        function cache keyed by id)."""
        import ray_tpu as ray

        sess = self._session(session_id)
        fn = cloudpickle.loads(blob)
        _mark_dynamic(fn)
        sess.funcs[func_id] = ray.remote(fn).options(**options) \
            if options else ray.remote(fn)
        return True

    async def client_task(self, session_id: str, func_id: str,
                          args_blob: bytes,
                          options: Optional[dict] = None) -> list:
        sess = self._session(session_id)
        fn = sess.funcs[func_id]
        if options:
            fn = fn.options(**options)
        args, kwargs = self._load_args(sess, args_blob)
        refs = await self._in_thread(fn.remote, *args, **kwargs)
        return self._track(sess, refs)

    async def client_register_actor_class(self, session_id: str,
                                          class_id: str, blob: bytes,
                                          options: dict) -> bool:
        import ray_tpu as ray

        sess = self._session(session_id)
        cls = cloudpickle.loads(blob)
        _mark_dynamic(cls)
        remote_cls = ray.remote(cls)
        if options:
            remote_cls = remote_cls.options(**options)
        sess.actor_classes[class_id] = remote_cls
        return True

    async def client_create_actor(self, session_id: str, class_id: str,
                                  args_blob: bytes,
                                  options: Optional[dict] = None) -> dict:
        sess = self._session(session_id)
        cls = sess.actor_classes[class_id]
        if options:
            cls = cls.options(**options)
        args, kwargs = self._load_args(sess, args_blob)
        handle = await self._in_thread(cls.remote, *args, **kwargs)
        sess.actors[handle.actor_id] = handle
        return {"actor_id": handle.actor_id,
                "methods": sorted(handle._methods)
                if hasattr(handle, "_methods") else []}

    async def client_actor_task(self, session_id: str, actor_id: str,
                                method_name: str, args_blob: bytes,
                                num_returns: Optional[int] = None) -> list:
        sess = self._session(session_id)
        handle = sess.actors[actor_id]
        args, kwargs = self._load_args(sess, args_blob)
        m = getattr(handle, method_name)
        if num_returns is not None:
            m = m.options(num_returns=num_returns)
        refs = await self._in_thread(m.remote, *args, **kwargs)
        return self._track(sess, refs)

    async def client_get_actor(self, session_id: str, name: str,
                               namespace: str = "") -> dict:
        import ray_tpu as ray

        sess = self._session(session_id)
        handle = await self._in_thread(
            ray.get_actor, name, namespace or sess.namespace)
        sess.actors[handle.actor_id] = handle
        return {"actor_id": handle.actor_id}

    async def client_kill_actor(self, session_id: str, actor_id: str,
                                no_restart: bool = True) -> bool:
        import ray_tpu as ray

        sess = self._session(session_id)
        handle = sess.actors[actor_id]
        await self._in_thread(ray.kill, handle, no_restart=no_restart)
        return True

    # -- cross-language surface (bytes in/out; consumed by the C++
    #    worker API, cpp/include/ray_tpu/client.h) ---------------------
    async def client_put_bytes(self, session_id: str,
                               payload: bytes) -> str:
        import ray_tpu as ray

        sess = self._session(session_id)
        ref = await self._in_thread(ray.put, payload)
        return self._track(sess, ref)[0]

    async def client_get_bytes(self, session_id: str, ref_id: str,
                               get_timeout: Optional[float] = None
                               ) -> bytes:
        import ray_tpu as ray

        sess = self._session(session_id)
        value = await self._in_thread(
            ray.get, sess.refs[ref_id], timeout=get_timeout)
        if not isinstance(value, (bytes, bytearray)):
            raise TypeError(
                f"cross-language results must be bytes, got "
                f"{type(value).__name__}")
        return bytes(value)

    async def client_task_by_name(self, session_id: str, func_name: str,
                                  payload: bytes) -> str:
        """Submit a registered cross-language function by name
        (reference: function-descriptor invocation,
        python/ray/cross_language.py)."""
        import ray_tpu as ray
        from ... import cross_language

        sess = self._session(session_id)
        cache_key = f"__crosslang__:{func_name}"
        fn = sess.funcs.get(cache_key)
        if fn is None:
            raw = await self._in_thread(
                cross_language.get_function, func_name)
            fn = ray.remote(raw)
            sess.funcs[cache_key] = fn
        ref = await self._in_thread(fn.remote, payload)
        return self._track(sess, ref)[0]

    async def client_register_cpp_worker(self, session_id: str,
                                         functions: list, host: str,
                                         port: int) -> bool:
        """A native (C++) worker announces the functions it serves.
        Python invokes them by descriptor via
        cross_language.cpp_function (reference: the reverse direction of
        client_task_by_name; cpp/src/ray/runtime/task/task_executor.cc
        registers C++ functions for by-descriptor execution)."""
        from ...cross_language import register_cpp_worker

        self._session(session_id)
        await self._in_thread(
            register_cpp_worker, list(functions), str(host), int(port))
        return True

    async def client_api(self, session_id: str, api_method: str) -> Any:
        """Read-only cluster info passthrough."""
        import ray_tpu as ray

        self._session(session_id)
        allowed = {
            "nodes": ray.nodes,
            "cluster_resources": ray.cluster_resources,
            "available_resources": ray.available_resources,
            "timeline": ray.timeline,
        }
        return await self._in_thread(allowed[api_method])

    async def client_ping(self, session_id: str = "") -> str:
        if session_id:
            try:
                self._session(session_id)  # refreshes last_seen
            except KeyError:
                pass
        return "pong"
