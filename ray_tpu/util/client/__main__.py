"""Standalone client server: ``python -m ray_tpu.util.client``.

Reference: the client server the reference starts from `ray start --head
--ray-client-server-port` (util/client/server/__main__ equivalent).
"""
import argparse
import os
import threading

# a helper service must not echo the cluster's worker logs
os.environ.setdefault("RAY_TPU_LOG_TO_DRIVER", "0")

import ray_tpu as ray
from .server import ClientServer

p = argparse.ArgumentParser("ray-tpu client server")
p.add_argument("--address", required=True, help="GCS host:port")
p.add_argument("--host", default="0.0.0.0")
p.add_argument("--port", type=int, default=10001)
args = p.parse_args()
ray.init(address=args.address)
srv = ClientServer(args.host, args.port)
print(f"CLIENT_SERVER_READY {srv.address[0]}:{srv.address[1]}", flush=True)
threading.Event().wait()
