"""Ray Client: remote drivers over one socket (``ray://host:port``).

Reference: python/ray/util/client/ (+ server/) — a gRPC proxy mode
where a machine OUTSIDE the cluster network runs driver code; the
server hosts per-client proxy state (object refs, actor handles,
exported functions) and executes API calls on the client's behalf
(worker.py:81 client Worker, server/server.py per-client servicer).

Needed here for the same reason: a direct ``ray_tpu.init(address=...)``
driver must share the head node's shm arena (local-only); ``ray://``
lifts that requirement to one TCP connection.
"""
from .server import ClientServer
from .worker import ClientWorker, ClientObjectRef, ClientActorHandle

__all__ = [
    "ClientServer", "ClientWorker", "ClientObjectRef",
    "ClientActorHandle",
]
