"""Placement groups: gang reservation of resources across nodes.

Reference: python/ray/util/placement_group.py (placement_group :146) +
GCS 2-phase bundle commit (gcs_placement_group_scheduler). On TPU the
primary use is gang-scheduling all hosts of a slice: bundles with
``{"TPU": n}`` pack onto one slice's hosts (ICI-contiguous) by the
label-aware packer in _private/scheduling.py.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from .._private.core_worker import global_worker
from .._private.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]]):
        self.id_hex = pg_id
        self.bundle_specs = bundles

    def ready(self, timeout: Optional[float] = None) -> bool:
        return self.wait(timeout)

    def wait(self, timeout: Optional[float] = None) -> bool:
        worker = global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            info = worker.gcs.get_placement_group(pg_id=self.id_hex)
            if info is not None and info["state"] == "CREATED":
                return True
            if info is not None and info["state"] == "REMOVED":
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.02)

    @property
    def placement(self) -> Optional[List[str]]:
        info = global_worker().gcs.get_placement_group(pg_id=self.id_hex)
        return None if info is None else info.get("placement")

    def __reduce__(self):
        return (PlacementGroup, (self.id_hex, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    worker = global_worker()
    pg_id = PlacementGroupID.of(worker.job_id).hex()
    res = worker.gcs.create_placement_group(
        spec={
            "pg_id": pg_id,
            "job_id": worker.job_id.hex(),
            "name": name,
            "bundles": [
                {k: float(v) for k, v in b.items()} for b in bundles
            ],
            "strategy": strategy,
            "detached": lifetime == "detached",
        }
    )
    if not res.get("ok"):
        raise ValueError(res.get("error", "placement group creation failed"))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    global_worker().gcs.remove_placement_group(pg_id=pg.id_hex)


def placement_group_table() -> List[dict]:
    return global_worker().gcs.get_all_placement_groups()
