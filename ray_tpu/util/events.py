"""Structured export events: an append-only JSONL audit stream.

Reference: src/ray/util/event.h + export_*.proto — every process can
emit typed events (task/actor/node/job state transitions) that an
aggregator ships for external consumption
(_private/event/export_event_logger.py). Here events append to
``<session_dir>/events/events_<source>.jsonl`` — one line per event,
schema {timestamp, source, event_type, severity, entity_id, data} —
and the GCS emits the control-plane transitions itself.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional


class EventLogger:
    def __init__(self, session_dir: str, source: str):
        self._dir = os.path.join(session_dir, "events")
        os.makedirs(self._dir, exist_ok=True)
        self._path = os.path.join(self._dir,
                                  f"events_{source}.jsonl")
        self._source = source
        self._lock = threading.Lock()
        self._fh = open(self._path, "a", buffering=1)

    def emit(self, event_type: str, entity_id: str = "",
             severity: str = "INFO",
             data: Optional[Dict[str, Any]] = None) -> None:
        rec = {
            "timestamp": time.time(),
            "source": self._source,
            "event_type": event_type,
            "severity": severity,
            "entity_id": entity_id,
            "data": data or {},
        }
        try:
            with self._lock:
                self._fh.write(json.dumps(rec, default=str) + "\n")
        except Exception:
            pass  # events must never take the emitter down

    def close(self):
        try:
            self._fh.close()
        except Exception:
            pass


def read_events(session_dir: str, source: Optional[str] = None) -> list:
    out = []
    d = os.path.join(session_dir, "events")
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if source and name != f"events_{source}.jsonl":
            continue
        with open(os.path.join(d, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass  # torn tail write
    out.sort(key=lambda e: e.get("timestamp", 0))
    return out
