"""State observability API: list/summarize cluster entities.

Reference: python/ray/util/state/api.py (`ray list actors/tasks/...`)
backed by StateAPIManager (state_manager.py:94) over GCS + per-node
sources. Here the sources are the GCS tables directly (actors, PGs,
jobs, nodes, task events) and per-raylet RPCs (workers, store objects),
queried through the connected driver's clients.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .._private.core_worker import global_worker


def _gcs():
    return global_worker().gcs


def list_nodes() -> List[Dict[str, Any]]:
    out = []
    for n in _gcs().get_all_nodes():
        out.append({
            "node_id": n["node_id"],
            "state": "ALIVE" if n.get("alive", True) else "DEAD",
            "is_head": n.get("is_head", False),
            "address": n.get("address"),
            "resources_total": n.get("total", n.get("resources", {})),
            "resources_available": n.get("available", {}),
            "labels": n.get("labels", {}),
        })
    return out


def list_actors(filters: Optional[Dict[str, Any]] = None
                ) -> List[Dict[str, Any]]:
    out = []
    for a in _gcs().get_all_actors():
        rec = {
            "actor_id": a.get("actor_id"),
            "state": a.get("state"),
            "name": a.get("name") or "",
            "namespace": a.get("namespace", ""),
            "class_name": a.get("class_name", ""),
            "node_id": a.get("node_id"),
            "pid": a.get("pid"),
            "restarts": a.get("restarts", 0),
            "detached": a.get("detached", False),
        }
        if _match(rec, filters):
            out.append(rec)
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    out = []
    for pg in _gcs().get_all_placement_groups():
        out.append({
            "placement_group_id": pg.get("pg_id", pg.get("id")),
            "state": pg.get("state"),
            "strategy": pg.get("strategy"),
            "bundles": pg.get("bundles"),
            "name": pg.get("name", ""),
        })
    return out


def list_jobs() -> List[Dict[str, Any]]:
    return list(_gcs().get_all_jobs())


def list_tasks(job_id: Optional[str] = None, limit: int = 1000
               ) -> List[Dict[str, Any]]:
    """Latest known status per task from the GCS task-event store
    (reference: GcsTaskManager gcs_task_manager.h:94)."""
    events = _gcs().get_task_events(job_id=job_id, limit=10 * limit)
    latest: Dict[str, dict] = {}
    order = {"PENDING": 0, "RETRYING": 1, "RUNNING": 2,
             "FINISHED": 3, "FAILED": 3}
    for e in events:
        tid = e.get("task_id")
        if tid is None:
            continue
        cur = latest.get(tid)
        if cur is None or e.get("ts", 0) >= cur.get("ts", 0):
            merged = dict(cur or {})
            merged.update({k: v for k, v in e.items() if v is not None})
            # never regress a terminal state with a stale event
            if cur and order.get(cur.get("state"), 0) > order.get(
                    e.get("state"), 0):
                merged["state"] = cur["state"]
            latest[tid] = merged
    out = [
        {
            "task_id": tid,
            "name": e.get("name", ""),
            "state": e.get("state"),
            "job_id": e.get("job_id"),
            "node_id": e.get("node_id"),
        }
        for tid, e in latest.items()
    ]
    return out[:limit]


def _fanout_raylets(method: str, timeout: float = 5.0, **kwargs
                    ) -> List[tuple]:
    """Call one RPC on every alive raylet concurrently; returns
    [(node, result)] for the nodes that answered — one slow node costs
    one timeout, not one per node."""
    import asyncio

    from .._private.rpc import EventLoopThread

    w = global_worker()
    nodes = [n for n in _gcs().get_all_nodes() if n.get("alive", True)]

    async def one(n):
        try:
            res = await asyncio.wait_for(
                w._pool.get(*n["address"]).call(method, **kwargs),
                timeout,
            )
            return (n, res)
        except Exception:
            return (n, None)

    async def all_():
        return await asyncio.gather(*(one(n) for n in nodes))

    results = EventLoopThread.get().run(all_(), timeout + 5.0)
    return [(n, r) for n, r in results if r is not None]


def list_workers() -> List[Dict[str, Any]]:
    out = []
    for n, info in _fanout_raylets("node_info"):
        for wid in info.get("workers", []):
            out.append({"worker_id": wid, "node_id": n["node_id"]})
    return out


def list_objects(limit: int = 10000) -> List[Dict[str, Any]]:
    """Objects sealed in every node's shm arena. (Inline objects live in
    their owners' memory stores and are not listed — same as the
    reference, which lists only plasma-backed objects.)"""
    out: List[Dict[str, Any]] = []
    for _, objs in _fanout_raylets("list_store_objects", timeout=10.0,
                                   limit=limit):
        out.extend(objs)
    return out[:limit]


def summarize_tasks(job_id: Optional[str] = None) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for t in list_tasks(job_id=job_id, limit=100000):
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for a in list_actors():
        counts[a["state"]] = counts.get(a["state"], 0) + 1
    return counts


def _match(rec: dict, filters: Optional[Dict[str, Any]]) -> bool:
    if not filters:
        return True
    return all(rec.get(k) == v for k, v in filters.items())
