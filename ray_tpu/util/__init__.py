from . import metrics  # noqa: F401
from . import scheduling_strategies  # noqa: F401
from . import state  # noqa: F401
from .placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
