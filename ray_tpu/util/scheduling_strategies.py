"""Scheduling strategy objects.

Reference: python/ray/util/scheduling_strategies.py — PlacementGroup (:15),
NodeAffinity (:41), NodeLabel (:135) strategies, passed to .options().
"""
from __future__ import annotations

from typing import Dict, Optional


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks
        )


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[Dict[str, str]] = None,
                 soft: Optional[Dict[str, str]] = None):
        self.hard = hard or {}
        self.soft = soft or {}
