"""Scalability-envelope probes (`python -m ray_tpu.scalability_envelope`).

Mirrors the reference's single-node scalability envelope
(reference: release/benchmarks/README.md:27-31 and
release/benchmarks/single_node/test_single_node.py): many task args,
many task returns, many-object ray.get, a deep task queue, and a
maximum-size object get. Reference numbers (v2.9.3, 1x m4.16xlarge,
release/release_logs/2.9.3/scalability/single_node.json):

    10,000 object args to a single task   17.30 s
    3,000 returns from a single task       7.03 s
    ray.get on 10,000 objects             26.53 s
    queue 1,000,000 tasks                193.74 s
    ray.get on a 100 GiB object           30.74 s

Counts scale down via env vars for small hosts; the JSON records the
counts actually used so ratios stay honest. The large-object probe is
capped by free /dev/shm (the reference machine had 256 GiB RAM).
Writes BENCH_envelope.json.
"""
from __future__ import annotations

import json
import os
import time
from typing import List

import numpy as np

import ray_tpu as ray

NUM_ARGS = int(os.environ.get("RAY_TPU_ENVELOPE_ARGS", "10000"))
NUM_RETURNS = int(os.environ.get("RAY_TPU_ENVELOPE_RETURNS", "3000"))
NUM_GET = int(os.environ.get("RAY_TPU_ENVELOPE_GET", "10000"))
NUM_QUEUED = int(os.environ.get("RAY_TPU_ENVELOPE_QUEUED", "1000000"))
LARGE_GIB_CAP = float(os.environ.get("RAY_TPU_ENVELOPE_LARGE_GIB", "8"))

REFERENCE = {
    "many task args": {"count": 10000, "seconds": 17.30},
    "many task returns": {"count": 3000, "seconds": 7.03},
    "ray.get many objects": {"count": 10000, "seconds": 26.53},
    "queue many tasks": {"count": 1000000, "seconds": 193.74},
    "large object get": {"gib": 100.0, "seconds": 30.74},
}


@ray.remote
def nop(*args):
    return None


@ray.remote
def nop_returns(n):
    return tuple(range(n))


def probe(name: str, fn, results: List[dict], **extra):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    ref = REFERENCE[name]
    row = {"name": name, "seconds": round(dt, 2), "reference": ref, **extra}
    print(f"{name}: {dt:.2f} s  (ref {ref['seconds']} s "
          f"@ {ref.get('count', ref.get('gib'))})", flush=True)
    results.append(row)


def main() -> List[dict]:
    results: List[dict] = []
    from ray_tpu.microbenchmark import bench_init

    bench_init()
    try:
        # warm the worker pool
        ray.get([nop.remote() for _ in range(20)])

        refs = [ray.put(0) for _ in range(NUM_ARGS)]
        probe("many task args",
              lambda: ray.get(nop.remote(*refs)),
              results, count=NUM_ARGS)
        del refs

        probe("many task returns",
              lambda: ray.get(
                  nop_returns.options(num_returns=NUM_RETURNS)
                  .remote(NUM_RETURNS)),
              results, count=NUM_RETURNS)

        # objects sized past the inline threshold (config
        # max_inline_object_size = 100 KiB) so this measures the SHM
        # store path — inline values would be pure memory-store reads
        big = np.zeros(16 * 1024, dtype=np.int64)  # 128 KiB each
        objs = [ray.put(big) for _ in range(NUM_GET)]
        probe("ray.get many objects",
              lambda: ray.get(objs),
              results, count=NUM_GET,
              object_bytes=big.nbytes)
        del objs

        def queue_many():
            batch = [nop.remote() for _ in range(NUM_QUEUED)]
            ray.get(batch)

        probe("queue many tasks", queue_many, results, count=NUM_QUEUED)

        # large object: bounded by free shm (value + serialized copy)
        free_gib = 4.0
        try:
            st = os.statvfs("/dev/shm")
            free_gib = st.f_bavail * st.f_frsize / (1 << 30)
        except OSError:
            pass
        gib = min(LARGE_GIB_CAP, max(0.25, free_gib * 0.35))
        arr = np.zeros(int(gib * (1 << 30) // 8), dtype=np.int64)
        ref_large = ray.put(arr)
        del arr
        t0 = time.perf_counter()
        out = ray.get(ref_large)
        # ray.get returns a zero-copy mmap view — timing it alone would
        # record ~0 s regardless of size. MATERIALIZE: touch every byte
        # so the number reflects real memory traffic, comparable to the
        # reference's (which deserializes a full copy).
        checksum = float(out.sum())
        dt = time.perf_counter() - t0
        assert checksum == 0.0
        ref = REFERENCE["large object get"]
        print(f"large object get: {gib:.2f} GiB in {dt:.2f} s "
              f"({gib / dt:.2f} GiB/s, fully materialized; ref "
              f"{ref['gib']} GiB in {ref['seconds']} s = "
              f"{ref['gib'] / ref['seconds']:.2f} GiB/s)",
              flush=True)
        results.append({
            "name": "large object get", "seconds": round(dt, 2),
            "gib": round(gib, 2), "gib_per_s": round(gib / dt, 2),
            "reference": ref,
            "note": ("zero-copy get + full page-touch materialization; "
                     "size capped by free /dev/shm on this host"),
        })
        del out
    finally:
        ray.shutdown()
    return results


if __name__ == "__main__":
    from ray_tpu.microbenchmark import write_bench_json

    out = main()
    write_bench_json("BENCH_envelope.json", {"probes": out})
