from .head import main

main()
