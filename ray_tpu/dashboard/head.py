"""Dashboard head: aiohttp server over the state/jobs/metrics APIs.

Reference: python/ray/dashboard/head.py (DashboardHead) +
http_server_head.py (aiohttp app), modules/node, modules/job/job_head.py
(REST job endpoints), modules/metrics, modules/log. Runs inside any
process connected to the cluster (a driver, or the standalone
``python -m ray_tpu.dashboard`` entry).
"""
from __future__ import annotations

import asyncio
import json
import os
import threading
from typing import Any, Optional

from .html import INDEX_HTML


def _json(data: Any, status: int = 200):
    from aiohttp import web

    return web.json_response(
        data, status=status, dumps=lambda d: json.dumps(d, default=str)
    )


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._runner = None
        self._started = threading.Event()

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "DashboardHead":
        from .._private.rpc import EventLoopThread

        loop = EventLoopThread.get().loop
        fut = asyncio.run_coroutine_threadsafe(self._start(), loop)
        fut.result(30)
        return self

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        r = app.router
        r.add_get("/", self._index)
        r.add_get("/api/version", self._version)
        r.add_get("/api/cluster_status", self._cluster_status)
        r.add_get("/api/nodes", self._nodes)
        r.add_get("/api/actors", self._actors)
        r.add_get("/api/tasks", self._tasks)
        r.add_get("/api/placement_groups", self._pgs)
        r.add_get("/api/workers", self._workers)
        r.add_get("/api/objects", self._objects)
        r.add_get("/api/summary", self._summary)
        r.add_get("/api/autoscaler", self._autoscaler)
        r.add_get("/api/timeline", self._timeline)
        r.add_get("/api/metrics", self._metrics)
        r.add_get("/api/jobs", self._jobs_list)
        r.add_post("/api/jobs", self._jobs_submit)
        r.add_get("/api/jobs/{id}", self._job_info)
        r.add_get("/api/jobs/{id}/logs", self._job_logs)
        r.add_post("/api/jobs/{id}/stop", self._job_stop)
        r.add_get("/api/logs/{node_id}", self._node_logs_list)
        r.add_get("/api/logs/{node_id}/{name}", self._node_log_file)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        # port=0 -> resolve the bound port
        for s in runner.sites:
            srv = getattr(s, "_server", None)
            if srv and srv.sockets:
                self.port = srv.sockets[0].getsockname()[1]
        self._runner = runner
        self._started.set()

    def stop(self):
        if self._runner is None:
            return
        from .._private.rpc import EventLoopThread

        loop = EventLoopThread.get().loop
        asyncio.run_coroutine_threadsafe(
            self._runner.cleanup(), loop).result(10)
        self._runner = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- blocking state calls run off the event loop ------------------
    async def _call(self, fn, *args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: fn(*args, **kwargs))

    # -- handlers -----------------------------------------------------
    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _version(self, request):
        import ray_tpu

        return _json({"version": getattr(ray_tpu, "__version__", "dev"),
                      "framework": "ray_tpu"})

    async def _cluster_status(self, request):
        from .._private.core_worker import global_worker

        return _json(await self._call(
            global_worker().gcs.get_cluster_status))

    async def _nodes(self, request):
        from ..util import state

        return _json(await self._call(state.list_nodes))

    async def _actors(self, request):
        from ..util import state

        return _json(await self._call(state.list_actors))

    async def _tasks(self, request):
        from ..util import state

        job_id = request.query.get("job_id")
        limit = int(request.query.get("limit", 1000))
        return _json(await self._call(state.list_tasks, job_id, limit))

    async def _pgs(self, request):
        from ..util import state

        return _json(await self._call(state.list_placement_groups))

    async def _workers(self, request):
        from ..util import state

        return _json(await self._call(state.list_workers))

    async def _objects(self, request):
        from ..util import state

        limit = int(request.query.get("limit", 1000))
        return _json(await self._call(state.list_objects, limit))

    async def _summary(self, request):
        from ..util import state

        return _json({
            "tasks": await self._call(state.summarize_tasks),
            "actors": await self._call(state.summarize_actors),
        })

    async def _autoscaler(self, request):
        from .._private.core_worker import global_worker

        return _json(await self._call(
            global_worker().gcs.get_autoscaler_state))

    async def _timeline(self, request):
        from .. import api

        return _json(await self._call(api.timeline))

    async def _metrics(self, request):
        """Aggregated Prometheus text from every node's metrics agent
        (reference: the dashboard scrapes per-node metrics agents)."""
        import aiohttp
        from aiohttp import web

        from .._private.core_worker import global_worker

        nodes = await self._call(global_worker().gcs.get_all_nodes)

        async def scrape(sess, n):
            addr = n["metrics_address"]
            try:
                async with sess.get(
                    f"http://{addr[0]}:{addr[1]}/metrics",
                    timeout=aiohttp.ClientTimeout(total=3),
                ) as resp:
                    return f"# node {n['node_id']}\n{await resp.text()}"
            except Exception:
                return None

        targets = [n for n in nodes
                   if n.get("metrics_address") and n.get("alive", True)]
        async with aiohttp.ClientSession() as sess:
            # concurrent scrape: total latency is one slow node, not
            # the sum over the fleet
            chunks = await asyncio.gather(
                *(scrape(sess, n) for n in targets))
        return web.Response(
            text="\n".join(c for c in chunks if c),
            content_type="text/plain")

    # -- jobs ---------------------------------------------------------
    def _job_client(self):
        from ..jobs import JobSubmissionClient

        return JobSubmissionClient()

    async def _jobs_list(self, request):
        return _json(await self._call(
            lambda: self._job_client().list_jobs()))

    async def _jobs_submit(self, request):
        body = await request.json()
        entrypoint = body.get("entrypoint")
        if not entrypoint:
            return _json({"error": "entrypoint required"}, status=400)

        def submit():
            return self._job_client().submit_job(
                entrypoint=entrypoint,
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
            )

        return _json({"submission_id": await self._call(submit)})

    async def _job_info(self, request):
        sid = request.match_info["id"]
        try:
            return _json(await self._call(
                lambda: self._job_client().get_job_info(sid)))
        except Exception as e:
            return _json({"error": str(e)}, status=404)

    async def _job_logs(self, request):
        from aiohttp import web

        sid = request.match_info["id"]
        try:
            logs = await self._call(
                lambda: self._job_client().get_job_logs(sid))
            return web.Response(text=logs, content_type="text/plain")
        except Exception as e:
            return _json({"error": str(e)}, status=404)

    async def _job_stop(self, request):
        sid = request.match_info["id"]
        try:
            return _json({"stopped": await self._call(
                lambda: self._job_client().stop_job(sid))})
        except Exception as e:
            return _json({"error": str(e)}, status=404)

    # -- logs (routed to the target node's raylet, which serves its
    #    own log dir — reference: per-node dashboard agent log module) --
    async def _raylet_call(self, node_id: str, method: str, **kwargs):
        from .._private.core_worker import global_worker

        w = global_worker()
        node = next(
            (n for n in await self._call(w.gcs.get_all_nodes)
             if n["node_id"] == node_id and n.get("alive", True)),
            None,
        )
        if node is None:
            return None
        return await w._pool.get(*node["address"]).call(
            method, timeout=10.0, **kwargs)

    async def _node_logs_list(self, request):
        files = await self._raylet_call(
            request.match_info["node_id"], "list_log_files")
        if files is None:
            return _json({"error": "unknown node"}, status=404)
        return _json(files)

    async def _node_log_file(self, request):
        from aiohttp import web

        text = await self._raylet_call(
            request.match_info["node_id"], "read_log_file",
            name=request.match_info["name"],
            tail_bytes=int(request.query.get("tail_bytes", 1 << 20)),
        )
        if text is None:
            return _json({"error": "not found"}, status=404)
        return web.Response(text=text, content_type="text/plain")


def main():
    import argparse

    import ray_tpu as ray

    p = argparse.ArgumentParser("ray-tpu dashboard")
    p.add_argument("--address", required=True, help="GCS host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args()
    # a helper service must not echo the cluster's worker logs into its
    # own log file (it is a driver, but not a user-facing one)
    os.environ.setdefault("RAY_TPU_LOG_TO_DRIVER", "0")
    ray.init(address=args.address)
    head = DashboardHead(args.host, args.port).start()
    print(f"DASHBOARD_READY {head.url}", flush=True)
    threading.Event().wait()


if __name__ == "__main__":
    main()
