"""Dashboard: cluster observability web UI + HTTP API.

Reference: python/ray/dashboard/ — head process (head.py, aiohttp
http_server_head.py) with pluggable modules (node, job, state, metrics,
log) and a React frontend. Here one aiohttp process serves a JSON API
over the state/job/metrics subsystems plus a single-file HTML UI
(no node/npm toolchain in the image; the API surface is what matters
for parity — the reference's React client is a consumer of the same
endpoints).
"""
from .head import DashboardHead

__all__ = ["DashboardHead"]
