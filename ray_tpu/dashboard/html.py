"""The single-file dashboard UI.

Reference: python/ray/dashboard/client/ is a 202-file React app; this
vanilla-JS page consumes the same API surface (nodes/actors/tasks/jobs/
placement groups/summary) with 2s polling — no build toolchain needed.
"""

INDEX_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f6f7f9; color: #1a202c; }
  header { background: #1a2233; color: #fff; padding: 10px 20px; display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header span { color: #9aa5b1; font-size: 12px; }
  nav { display: flex; gap: 4px; padding: 8px 20px 0; }
  nav button { border: 0; background: #e2e8f0; padding: 8px 14px; border-radius: 6px 6px 0 0; cursor: pointer; font-size: 13px; }
  nav button.active { background: #fff; font-weight: 600; }
  main { background: #fff; margin: 0 20px 20px; padding: 16px; border-radius: 0 6px 6px 6px; min-height: 300px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 6px 10px; border-bottom: 1px solid #e2e8f0; }
  th { color: #4a5568; font-weight: 600; background: #f8fafc; position: sticky; top: 0; }
  .ALIVE, .RUNNING, .CREATED, .SUCCEEDED, .FINISHED { color: #15803d; font-weight: 600; }
  .DEAD, .FAILED, .STOPPED { color: #b91c1c; font-weight: 600; }
  .PENDING, .PENDING_CREATION, .RESTARTING, .RETRYING { color: #b45309; font-weight: 600; }
  #summary { display: flex; gap: 16px; flex-wrap: wrap; margin-bottom: 12px; }
  .tile { background: #f8fafc; border: 1px solid #e2e8f0; border-radius: 6px; padding: 10px 16px; min-width: 110px; }
  .tile .v { font-size: 22px; font-weight: 700; }
  .tile .k { font-size: 11px; color: #64748b; text-transform: uppercase; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span id="status">connecting…</span></header>
<nav id="tabs"></nav>
<main>
  <div id="summary"></div>
  <div id="content">loading…</div>
</main>
<script>
const TABS = {
  nodes: {url: "/api/nodes", cols: ["node_id","state","is_head","address","resources_total","resources_available"]},
  actors: {url: "/api/actors", cols: ["actor_id","state","name","class_name","node_id","restarts"]},
  tasks: {url: "/api/tasks", cols: ["task_id","name","state","job_id","node_id"]},
  jobs: {url: "/api/jobs", cols: ["submission_id","status","entrypoint","start_time","end_time"]},
  placement_groups: {url: "/api/placement_groups", cols: ["placement_group_id","state","strategy","bundles"]},
  autoscaler: {url: "/api/autoscaler", raw: true},
};
let active = "nodes";
const tabsEl = document.getElementById("tabs");
for (const name of Object.keys(TABS)) {
  const b = document.createElement("button");
  b.textContent = name.replace("_", " ");
  b.onclick = () => { active = name; render(); refresh(); };
  b.id = "tab-" + name;
  tabsEl.appendChild(b);
}
function render() {
  for (const name of Object.keys(TABS))
    document.getElementById("tab-" + name).className = name === active ? "active" : "";
}
function esc(s) {
  return s.replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;"
  })[c]);
}
function cell(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "object") return esc(JSON.stringify(v));
  return esc(String(v));
}
async function refresh() {
  try {
    const t = TABS[active];
    const [data, summary, status] = await Promise.all([
      fetch(t.url).then(r => r.json()),
      fetch("/api/summary").then(r => r.json()),
      fetch("/api/cluster_status").then(r => r.json()),
    ]);
    document.getElementById("status").textContent =
      `uptime ${Math.round(status.uptime_s)}s · ${status.nodes.filter(n=>n.alive!==false).length} nodes · ${status.num_actors} actors`;
    const sumEl = document.getElementById("summary");
    sumEl.innerHTML = "";
    const tiles = Object.assign(
      {},
      Object.fromEntries(Object.entries(summary.tasks || {}).map(([k,v]) => ["tasks " + k, v])),
      Object.fromEntries(Object.entries(summary.actors || {}).map(([k,v]) => ["actors " + k, v])));
    for (const [k, v] of Object.entries(tiles)) {
      const d = document.createElement("div");
      d.className = "tile";
      d.innerHTML = `<div class="v">${v}</div><div class="k">${k}</div>`;
      sumEl.appendChild(d);
    }
    const el = document.getElementById("content");
    if (t.raw) { el.innerHTML = "<pre>" + JSON.stringify(data, null, 2) + "</pre>"; return; }
    if (!Array.isArray(data) || !data.length) { el.textContent = "(empty)"; return; }
    const cols = t.cols.filter(c => data.some(r => c in r));
    let html = "<table><tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
    for (const row of data.slice(0, 500)) {
      html += "<tr>" + cols.map(c => {
        const v = cell(row[c]);
        // class names come from a server-side state enum; still
        // whitelist to keep attribute context injection-proof
        const safe = /^[A-Z_]+$/.test(v) ? v : "";
        const cls = (c === "state" || c === "status") && safe
          ? ` class="${safe}"` : "";
        return `<td${cls}>${v}</td>`;
      }).join("") + "</tr>";
    }
    el.innerHTML = html + "</table>";
  } catch (e) {
    document.getElementById("status").textContent = "error: " + e;
  }
}
render();
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
