"""Builds the native shared libraries on first import (cached by mtime)."""
from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))

_LIBS = {
    "libshmstore.so": ["shm_store.cpp"],
}


def lib_path(name: str) -> str:
    return os.path.join(_DIR, name)


def ensure_built(name: str = "libshmstore.so") -> str:
    sources = [os.path.join(_DIR, s) for s in _LIBS[name]]
    out = lib_path(name)
    if os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in sources
    ):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *sources, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out
