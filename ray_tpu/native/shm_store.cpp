// TPU-native node-local shared-memory object store.
//
// Role in the framework: the per-node object store holding sealed immutable
// objects (task args/returns, dataset blocks), equivalent to the reference's
// plasma store (reference: src/ray/object_manager/plasma/store.h:55,
// obj_lifecycle_mgr.h, eviction_policy.h, dlmalloc.cc).
//
// Redesign rationale: plasma is a *server* -- every create/get crosses a unix
// socket with fd-passing (reference: plasma/client.cc, fling.cc). Here the
// store is a *library*: one arena file under /dev/shm mapped by every process
// on the node; a process-shared robust pthread mutex + condvar in the arena
// header serialize metadata updates. Hot-path create/seal/get are pure memory
// ops (sub-microsecond), and readers get zero-copy views like plasma's mmap
// reads. Crash-safety comes from the robust mutex (EOWNERDEAD ->
// pthread_mutex_consistent) plus refcount reconciliation by the raylet.
//
// Layout:  [Header][object table: Entry[cap]][heap: boundary-tag allocator]
// All cross-process references are offsets from the arena base.
//
// Exported C API (ctypes-friendly): shm_store_{open,close,create,seal,get,
// release,contains,delete,evict,stats,list}.

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#endif

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5250545553544F52ULL;  // "RPTUSTOR"
constexpr int kIdLen = 20;
constexpr uint8_t kEmpty = 0, kCreated = 1, kSealed = 2, kTomb = 3;

struct Entry {
  uint8_t id[kIdLen];
  uint8_t state;
  uint8_t pending_delete;
  uint8_t pad[2];
  int32_t refcount;
  uint64_t data_off;
  uint64_t data_size;
  uint64_t lru;  // last-touch tick for LRU eviction
};

// Per-attached-process ref ledger: records which objects this client holds
// read refs on, so a crashed client's refs can be reconciled away (the
// reference's plasma store does this on client-socket disconnect,
// src/ray/object_manager/plasma/store.cc DisconnectClient; with no server we
// reconcile by pid liveness instead).
constexpr uint64_t kMaxClients = 256;
constexpr uint64_t kClientRefCap = 4096;  // open-addressed (id -> count) map

struct ClientRef {
  uint8_t id[kIdLen];
  uint32_t count;  // 0 = empty slot
};

struct ClientSlot {
  int64_t pid;  // 0 = free
  uint64_t nrefs;
  ClientRef refs[kClientRefCap];
};

struct Header {
  uint64_t magic;
  uint64_t arena_size;
  pthread_mutex_t mutex;
  pthread_cond_t cond;  // signaled on seal and on delete (space freed)
  uint64_t table_off;
  uint64_t table_cap;
  uint64_t clients_off;
  uint64_t heap_off;
  uint64_t heap_size;
  uint64_t lru_clock;
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t num_evictions;
  // 1 (default): create evicts LRU objects under pressure (standalone
  // arenas). 0: create returns OOM instead, so an external policy
  // (the raylet's spill-to-disk) decides — silent eviction would drop
  // objects whose owners still hold references (reference: plasma
  // never evicts referenced objects; the CreateRequestQueue
  // blocks/spills, store.h:55 + eviction_policy.h).
  uint64_t autoevict;
  uint64_t hwm_bytes;  // high-water mark of used_bytes (observability)
};

// Boundary-tag heap block header. Blocks are 64-byte aligned; `size` includes
// the header. Free blocks are linked through an intrusive free list.
struct Block {
  uint64_t size;       // total size incl. header; low bit = allocated flag
  uint64_t prev_size;  // size of physically-previous block (0 if first)
  uint64_t next_free;  // offsets into heap; valid when free
  uint64_t prev_free;
};

constexpr uint64_t kAlign = 64;
constexpr uint64_t kBlockHdr = sizeof(Block);
constexpr uint64_t kNullOff = ~0ULL;

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct Store {
  uint8_t* base;
  Header* hdr;
  int64_t slot_idx;  // this process's ClientSlot index, -1 if none
};

inline Entry* table(Store* s) {
  return reinterpret_cast<Entry*>(s->base + s->hdr->table_off);
}
inline ClientSlot* clients(Store* s) {
  return reinterpret_cast<ClientSlot*>(s->base + s->hdr->clients_off);
}
inline Block* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<Block*>(s->base + off);
}

// The free-list head lives in the 8 bytes right before heap_off.
inline uint64_t& free_head(Store* s) {
  return *reinterpret_cast<uint64_t*>(s->base + s->hdr->heap_off - 8);
}

inline uint64_t blk_size(Block* b) { return b->size & ~1ULL; }
inline bool blk_used(Block* b) { return b->size & 1ULL; }

void freelist_push(Store* s, uint64_t off) {
  Block* b = block_at(s, off);
  b->next_free = free_head(s);
  b->prev_free = kNullOff;
  if (free_head(s) != kNullOff) block_at(s, free_head(s))->prev_free = off;
  free_head(s) = off;
}

void freelist_remove(Store* s, uint64_t off) {
  Block* b = block_at(s, off);
  if (b->prev_free != kNullOff)
    block_at(s, b->prev_free)->next_free = b->next_free;
  else
    free_head(s) = b->next_free;
  if (b->next_free != kNullOff) block_at(s, b->next_free)->prev_free = b->prev_free;
}

uint64_t heap_end(Store* s) { return s->hdr->heap_off + s->hdr->heap_size; }

// Allocate `need` payload bytes; returns payload offset or kNullOff.
uint64_t heap_alloc(Store* s, uint64_t need) {
  uint64_t want = align_up(need + kBlockHdr);
  uint64_t off = free_head(s);
  while (off != kNullOff) {
    Block* b = block_at(s, off);
    if (blk_size(b) >= want) {
      freelist_remove(s, off);
      uint64_t remain = blk_size(b) - want;
      if (remain >= kBlockHdr + kAlign) {
        // split
        uint64_t tail_off = off + want;
        Block* tail = block_at(s, tail_off);
        tail->size = remain;  // free
        tail->prev_size = want;
        b->size = want | 1ULL;
        // fix next block's prev_size
        uint64_t nxt = tail_off + remain;
        if (nxt < heap_end(s)) block_at(s, nxt)->prev_size = remain;
        freelist_push(s, tail_off);
      } else {
        b->size = blk_size(b) | 1ULL;
      }
      s->hdr->used_bytes += blk_size(b);
      if (s->hdr->used_bytes > s->hdr->hwm_bytes)
        s->hdr->hwm_bytes = s->hdr->used_bytes;
      return off + kBlockHdr;
    }
    off = b->next_free;
  }
  return kNullOff;
}

void heap_free(Store* s, uint64_t payload_off) {
  uint64_t off = payload_off - kBlockHdr;
  Block* b = block_at(s, off);
  s->hdr->used_bytes -= blk_size(b);
  b->size = blk_size(b);  // clear used bit
  // coalesce with next
  uint64_t nxt_off = off + blk_size(b);
  if (nxt_off < heap_end(s)) {
    Block* nxt = block_at(s, nxt_off);
    if (!blk_used(nxt)) {
      freelist_remove(s, nxt_off);
      b->size = blk_size(b) + blk_size(nxt);
    }
  }
  // coalesce with prev
  if (b->prev_size) {
    uint64_t prv_off = off - b->prev_size;
    Block* prv = block_at(s, prv_off);
    if (!blk_used(prv)) {
      freelist_remove(s, prv_off);
      prv->size = blk_size(prv) + blk_size(b);
      off = prv_off;
      b = prv;
    }
  }
  uint64_t after = off + blk_size(b);
  if (after < heap_end(s)) block_at(s, after)->prev_size = blk_size(b);
  freelist_push(s, off);
}

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

Entry* find_entry(Store* s, const uint8_t* id, bool for_insert) {
  Entry* t = table(s);
  uint64_t cap = s->hdr->table_cap;
  uint64_t i = hash_id(id) % cap;
  Entry* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < cap; probe++, i = (i + 1) % cap) {
    Entry* e = &t[i];
    if (e->state == kEmpty) return for_insert ? (first_tomb ? first_tomb : e) : nullptr;
    if (e->state == kTomb) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return first_tomb;  // table full of tombstones/entries
}

// --- per-client ref ledger (caller holds the arena lock) ---
constexpr uint32_t kRefTomb = 0xFFFFFFFFu;

void ledger_add(Store* s, const uint8_t* id) {
  if (s->slot_idx < 0) return;
  ClientSlot* c = &clients(s)[s->slot_idx];
  uint64_t i = hash_id(id) % kClientRefCap;
  int64_t first_tomb = -1;
  for (uint64_t p = 0; p < kClientRefCap; p++, i = (i + 1) % kClientRefCap) {
    ClientRef* r = &c->refs[i];
    if (r->count == 0) {
      ClientRef* dst = first_tomb >= 0 ? &c->refs[first_tomb] : r;
      memcpy(dst->id, id, kIdLen);
      dst->count = 1;
      c->nrefs++;
      return;
    }
    if (r->count == kRefTomb) {
      if (first_tomb < 0) first_tomb = (int64_t)i;
      continue;
    }
    if (memcmp(r->id, id, kIdLen) == 0) {
      r->count++;
      return;
    }
  }
  if (first_tomb >= 0) {
    ClientRef* dst = &c->refs[first_tomb];
    memcpy(dst->id, id, kIdLen);
    dst->count = 1;
    c->nrefs++;
    return;
  }
  // ledger full: ref still counted in the entry, just not reclaimable on
  // crash. Harmless for liveness, only weakens crash cleanup.
}

// Returns 1 if this client's ledger held (and dropped) a ref, 0 otherwise.
int ledger_remove(Store* s, const uint8_t* id) {
  if (s->slot_idx < 0) return 1;  // no ledger: can't validate, allow
  ClientSlot* c = &clients(s)[s->slot_idx];
  uint64_t i = hash_id(id) % kClientRefCap;
  for (uint64_t p = 0; p < kClientRefCap; p++, i = (i + 1) % kClientRefCap) {
    ClientRef* r = &c->refs[i];
    if (r->count == 0) return 0;
    if (r->count != kRefTomb && memcmp(r->id, id, kIdLen) == 0) {
      if (--r->count == 0) {
        r->count = kRefTomb;
        c->nrefs--;
      }
      return 1;
    }
  }
  return 0;
}

// Drop one ref on an entry, completing a deferred delete if it hits zero.
void entry_unref(Store* s, Entry* e) {
  if (e->state != kSealed && e->state != kCreated) return;  // already gone
  if (e->refcount > 0) e->refcount--;
  if (e->refcount == 0 && e->pending_delete) {
    heap_free(s, e->data_off);
    e->state = kTomb;
    e->pending_delete = 0;
    s->hdr->num_objects--;
    pthread_cond_broadcast(&s->hdr->cond);
  }
}

// Release every ref held in a client slot (close or dead-process cleanup).
void drop_slot_refs(Store* s, ClientSlot* c) {
  for (uint64_t i = 0; i < kClientRefCap && c->nrefs > 0; i++) {
    ClientRef* r = &c->refs[i];
    if (r->count == 0 || r->count == kRefTomb) continue;
    Entry* e = find_entry(s, r->id, false);
    if (e && (e->state == kSealed || e->state == kCreated)) {
      for (uint32_t k = 0; k < r->count; k++) entry_unref(s, e);
    }
    r->count = 0;
    c->nrefs--;
  }
  memset(c->refs, 0, sizeof(c->refs));
  c->nrefs = 0;
  c->pid = 0;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // Previous holder died mid-critical-section. Metadata may be mid-update;
    // counters are reconciled by the raylet, structure updates are ordered so
    // the worst case is a leaked block. Mark consistent and continue.
    pthread_mutex_consistent(&s->hdr->mutex);
  } else if (rc == ENOTRECOVERABLE) {
    // Should be unreachable (every EOWNERDEAD path marks consistent); better
    // to kill this process than run lockless over shared metadata.
    fprintf(stderr, "shm_store: arena mutex unrecoverable, aborting\n");
    abort();
  }
}
void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// Evict LRU sealed objects with refcount==0 until at least `need` bytes are
// freed. One scan collects a batch of the oldest candidates (avoids the
// O(victims * table_cap) rescan-per-victim the naive loop would cost under
// the global lock); the caller loops if fragmentation still blocks the
// allocation. Caller holds the lock. Returns bytes freed.
constexpr int kEvictBatch = 64;

uint64_t evict_lru(Store* s, uint64_t need) {
  Entry* t = table(s);
  // Collect up to kEvictBatch candidates with the smallest lru ticks
  // (insertion into a small array kept sorted ascending by lru).
  Entry* batch[kEvictBatch];
  int n = 0;
  for (uint64_t i = 0; i < s->hdr->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state != kSealed || e->refcount != 0) continue;
    if (n < kEvictBatch || e->lru < batch[n - 1]->lru) {
      int j = (n < kEvictBatch) ? n : n - 1;
      while (j > 0 && batch[j - 1]->lru > e->lru) {
        batch[j] = batch[j - 1];
        j--;
      }
      batch[j] = e;
      if (n < kEvictBatch) n++;
    }
  }
  uint64_t freed = 0;
  for (int i = 0; i < n && freed < need; i++) {
    freed += batch[i]->data_size;
    heap_free(s, batch[i]->data_off);
    batch[i]->state = kTomb;
    s->hdr->num_objects--;
    s->hdr->num_evictions++;
  }
  return freed;
}

// Streaming (non-temporal) copy for large put payloads: a cached memcpy
// pays read-for-ownership traffic on every destination line, halving the
// effective write bandwidth into the arena. NT stores skip the RFO. Only
// worth it past ~1 MiB (below that the data is about to be re-read from
// cache anyway). Runtime-dispatched so the library loads on CPUs
// without AVX2; non-x86 builds compile the plain-memcpy fallback only.
#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("avx2"))) static void nt_copy_avx2(
    uint8_t* d, const uint8_t* s, uint64_t n) {
  uint64_t head = (32 - (reinterpret_cast<uintptr_t>(d) & 31)) & 31;
  if (head > n) head = n;
  memcpy(d, s, head);
  d += head;
  s += head;
  n -= head;
  uint64_t vec = n & ~static_cast<uint64_t>(127);
  for (uint64_t i = 0; i < vec; i += 128) {
    __m256i a = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s + i));
    __m256i b =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s + i + 32));
    __m256i c =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s + i + 64));
    __m256i e =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s + i + 96));
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d + i), a);
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d + i + 32), b);
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d + i + 64), c);
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d + i + 96), e);
  }
  _mm_sfence();
  memcpy(d + vec, s + vec, n - vec);
}
#endif  // x86

}  // namespace

extern "C" {

// GIL-free bulk copy (callers: serialization.write_into's out-of-band
// buffer copies). Dispatches to NT stores when profitable and supported.
void shm_copy_fast(void* dst, const void* src, uint64_t n) {
#if defined(__x86_64__) || defined(__i386__)
  if (n >= (1u << 20) && __builtin_cpu_supports("avx2")) {
    nt_copy_avx2(reinterpret_cast<uint8_t*>(dst),
                 reinterpret_cast<const uint8_t*>(src), n);
    return;
  }
#endif
  memcpy(dst, src, n);
}

// Opens (creating if needed) the arena file. Returns opaque handle or null.
// The creator prefaults the whole arena (MAP_POPULATE) so puts never pay
// first-touch zero-fill faults on the hot path; attaching clients map lazily
// and only pay cheap minor faults on pages that already exist.
void* shm_store_open(const char* path, uint64_t arena_size, int create) {
  arena_size &= ~(kAlign - 1);  // boundary tags steal the low size bit
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  bool init = false;
  if (st.st_size == 0) {
    if (!create) { close(fd); return nullptr; }
    if (ftruncate(fd, (off_t)arena_size) != 0) { close(fd); return nullptr; }
    init = true;
  } else {
    arena_size = (uint64_t)st.st_size;
  }
  // No MAP_POPULATE: prefaulting a multi-GB tmpfs arena takes seconds and
  // commits every page up front; tmpfs pages fault in zeroed on demand.
  void* mem = mmap(nullptr, arena_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  // Advisory: on kernels with shmem THP enabled (shmem_enabled=advise),
  // 2MiB mappings cut TLB pressure on the large-object memcpy path
  // (plasma similarly supports hugepage-backed arenas). No-op elsewhere.
  madvise(mem, arena_size, MADV_HUGEPAGE);
  Store* s = new Store();
  s->base = reinterpret_cast<uint8_t*>(mem);
  s->hdr = reinterpret_cast<Header*>(mem);
  if (init) {
    Header* h = s->hdr;
    memset(h, 0, sizeof(Header));
    h->arena_size = arena_size;
    h->autoevict = 1;
    // size table: one entry per expected 16KB of heap, min 4096 slots,
    // capped at 1M (a fresh ftruncate'd tmpfs file reads as zeros, so no
    // memset is needed -- zero == kEmpty/free slot).
    uint64_t cap = arena_size / 16384;
    if (cap < 4096) cap = 4096;
    if (cap > (1ULL << 20)) cap = (1ULL << 20);
    h->table_off = align_up(sizeof(Header));
    h->table_cap = cap;
    uint64_t table_bytes = cap * sizeof(Entry);
    h->clients_off = align_up(h->table_off + table_bytes);
    uint64_t clients_bytes = kMaxClients * sizeof(ClientSlot);
    uint64_t heap_off = align_up(h->clients_off + clients_bytes + 8);
    if (heap_off + kAlign > arena_size) {
      // metadata (size table + client ref ledgers) doesn't fit: an
      // unsigned heap_size would wrap and later writes would scribble
      // past the mapping — fail loudly instead
      munmap(mem, arena_size);
      unlink(path);
      delete s;
      return nullptr;
    }
    h->heap_off = heap_off;
    h->heap_size = (arena_size - heap_off) & ~(kAlign - 1);
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
    pthread_cond_init(&h->cond, &ca);
    // heap: single free block
    free_head(s) = kNullOff;
    Block* b = block_at(s, h->heap_off);
    b->size = h->heap_size;
    b->prev_size = 0;
    freelist_push(s, h->heap_off);
    __sync_synchronize();
    h->magic = kMagic;
  } else {
    // wait for initializer to finish
    for (int i = 0; i < 10000 && s->hdr->magic != kMagic; i++) usleep(1000);
    if (s->hdr->magic != kMagic) { munmap(mem, arena_size); delete s; return nullptr; }
  }
  // claim a client slot for crash-reconcilable ref tracking
  s->slot_idx = -1;
  lock(s);
  ClientSlot* cs = clients(s);
  for (uint64_t i = 0; i < kMaxClients; i++) {
    if (cs[i].pid == 0) {
      cs[i].pid = (int64_t)getpid();
      cs[i].nrefs = 0;
      s->slot_idx = (int64_t)i;
      break;
    }
  }
  unlock(s);
  return s;
}

void shm_store_close(void* hs) {
  Store* s = reinterpret_cast<Store*>(hs);
  if (s->slot_idx >= 0) {
    lock(s);
    drop_slot_refs(s, &clients(s)[s->slot_idx]);
    unlock(s);
  }
  munmap(s->base, s->hdr->arena_size);
  delete s;
}

// Reconcile refs of dead clients (raylet calls this periodically). Also
// deletes abandoned unsealed objects. Returns number of slots cleaned.
int shm_store_reconcile(void* hs) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  int cleaned = 0;
  ClientSlot* cs = clients(s);
  for (uint64_t i = 0; i < kMaxClients; i++) {
    if (cs[i].pid != 0 && kill((pid_t)cs[i].pid, 0) != 0 && errno == ESRCH) {
      drop_slot_refs(s, &cs[i]);
      cleaned++;
    }
  }
  // garbage-collect creates abandoned by dead processes
  Entry* t = table(s);
  for (uint64_t i = 0; i < s->hdr->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state == kCreated && e->refcount == 0) {
      heap_free(s, e->data_off);
      e->state = kTomb;
      s->hdr->num_objects--;
    }
  }
  unlock(s);
  return cleaned;
}

uint64_t shm_store_base(void* hs) {
  return reinterpret_cast<uint64_t>(reinterpret_cast<Store*>(hs)->base);
}

// rc: 0 ok; -1 already exists; -2 out of memory (after eviction attempts).
// On success *out_off is the payload offset (usable with the python mmap).
int shm_store_create(void* hs, const uint8_t* id, uint64_t size, uint64_t* out_off) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  Entry* e = find_entry(s, id, true);
  if (e && e->state != kEmpty && e->state != kTomb) { unlock(s); return -1; }
  uint64_t off = heap_alloc(s, size);
  // Evicting `size` bytes total may not produce `size` *contiguous* bytes
  // (fragmentation), so loop: evict LRU victims and retry until the
  // allocation succeeds or no evictable objects remain. Skipped when
  // autoevict is off (spill-managed arenas): the caller gets -2 and
  // the node policy spills instead of silently dropping live objects.
  while (off == kNullOff) {
    if (!s->hdr->autoevict) break;
    if (evict_lru(s, size) == 0) break;
    off = heap_alloc(s, size);
  }
  if (off == kNullOff) { unlock(s); return -2; }
  if (!e) { heap_free(s, off); unlock(s); return -3; }  // table full
  memcpy(e->id, id, kIdLen);
  e->state = kCreated;
  e->pending_delete = 0;
  e->refcount = 1;  // creator holds a ref until seal
  e->data_off = off;
  e->data_size = size;
  e->lru = ++s->hdr->lru_clock;
  s->hdr->num_objects++;
  ledger_add(s, id);
  *out_off = off;
  unlock(s);
  return 0;
}

int shm_store_seal(void* hs, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state != kCreated) { unlock(s); return -1; }
  e->state = kSealed;
  ledger_remove(s, id);
  entry_unref(s, e);  // drop creator ref
  pthread_cond_broadcast(&s->hdr->cond);
  unlock(s);
  return 0;
}

// Blocking get: waits up to timeout_ms for the object to be sealed.
// rc: 0 ok (refcount incremented); -1 timeout/not found.
int shm_store_get(void* hs, const uint8_t* id, int64_t timeout_ms,
                  uint64_t* out_off, uint64_t* out_size) {
  Store* s = reinterpret_cast<Store*>(hs);
  struct timespec deadline;
  clock_gettime(CLOCK_MONOTONIC, &deadline);
  deadline.tv_sec += timeout_ms / 1000;
  deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (deadline.tv_nsec >= 1000000000L) { deadline.tv_sec++; deadline.tv_nsec -= 1000000000L; }
  lock(s);
  while (true) {
    Entry* e = find_entry(s, id, false);
    if (e && e->state == kSealed) {
      e->refcount++;
      ledger_add(s, id);
      e->lru = ++s->hdr->lru_clock;
      *out_off = e->data_off;
      *out_size = e->data_size;
      unlock(s);
      return 0;
    }
    if (timeout_ms == 0) { unlock(s); return -1; }
    int rc = pthread_cond_timedwait(&s->hdr->cond, &s->hdr->mutex, &deadline);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&s->hdr->mutex);
    if (rc == ETIMEDOUT) { unlock(s); return -1; }
  }
}

int shm_store_release(void* hs, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || (e->state != kSealed && e->state != kCreated)) { unlock(s); return -1; }
  // Only drop the entry ref if this client actually holds one (otherwise a
  // buggy double-release could steal another client's pin and expose its
  // zero-copy views to eviction).
  if (ledger_remove(s, id)) entry_unref(s, e);
  unlock(s);
  return 0;
}

int shm_store_contains(void* hs, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  Entry* e = find_entry(s, id, false);
  int rc = (e && e->state == kSealed) ? 1 : 0;
  unlock(s);
  return rc;
}

// Delete (or mark pending-delete if readers hold refs). Aborts unsealed
// objects too (creator crash cleanup).
int shm_store_delete(void* hs, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state == kEmpty || e->state == kTomb) { unlock(s); return -1; }
  // Drain this client's own refs on the id first (e.g. creator abandoning an
  // unsealed create), so a later close/reconcile can't unref a future
  // incarnation of the same id.
  if (s->slot_idx >= 0) {
    while (ledger_remove(s, id)) {
      if (e->refcount > 0) e->refcount--;
    }
  }
  if (e->refcount > 0 && e->state == kSealed) {
    e->pending_delete = 1;
  } else {
    heap_free(s, e->data_off);
    e->state = kTomb;
    s->hdr->num_objects--;
    pthread_cond_broadcast(&s->hdr->cond);
  }
  unlock(s);
  return 0;
}

uint64_t shm_store_hwm(void* hs) {
  return reinterpret_cast<Store*>(hs)->hdr->hwm_bytes;
}

void shm_store_set_autoevict(void* hs, int enabled) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  s->hdr->autoevict = enabled ? 1 : 0;
  unlock(s);
}

uint64_t shm_store_evict(void* hs, uint64_t nbytes) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  uint64_t freed = evict_lru(s, nbytes);
  unlock(s);
  return freed;
}

void shm_store_stats(void* hs, uint64_t* used, uint64_t* capacity,
                     uint64_t* num_objects, uint64_t* num_evictions) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  *used = s->hdr->used_bytes;
  *capacity = s->hdr->heap_size;
  *num_objects = s->hdr->num_objects;
  *num_evictions = s->hdr->num_evictions;
  unlock(s);
}

// Copies up to max_ids sealed object ids (20 bytes each) into out; returns count.
uint64_t shm_store_list(void* hs, uint8_t* out, uint64_t max_ids) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  uint64_t n = 0;
  Entry* t = table(s);
  for (uint64_t i = 0; i < s->hdr->table_cap && n < max_ids; i++) {
    if (t[i].state == kSealed) {
      memcpy(out + n * kIdLen, t[i].id, kIdLen);
      n++;
    }
  }
  unlock(s);
  return n;
}

// Like shm_store_list but also writes each entry's last-touch LRU tick so
// callers (the raylet's spill policy) can order coldest-first.
uint64_t shm_store_list_lru(void* hs, uint8_t* out, uint64_t* ticks,
                            uint64_t max_ids) {
  Store* s = reinterpret_cast<Store*>(hs);
  lock(s);
  uint64_t n = 0;
  Entry* t = table(s);
  for (uint64_t i = 0; i < s->hdr->table_cap && n < max_ids; i++) {
    if (t[i].state == kSealed) {
      memcpy(out + n * kIdLen, t[i].id, kIdLen);
      ticks[n] = t[i].lru;
      n++;
    }
  }
  unlock(s);
  return n;
}

}  // extern "C"
