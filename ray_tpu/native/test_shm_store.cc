// Native-level unit tests for the shared-memory store.
//
// Reference analogue: the C++ unit tests under
// src/ray/object_manager/plasma/test/ run via Bazel+gtest with
// ASan/TSan configs (.bazelrc:114-133). No gtest in this image, so
// these are assert-based; the pytest wrapper (tests/test_native_store.py)
// compiles and runs them twice — plain and under
// -fsanitize=address,undefined — which is what the sanitizer CI configs
// buy the reference.
//
// Build: g++ -std=c++17 -O1 -g [-fsanitize=address,undefined] \
//            ray_tpu/native/test_shm_store.cc -ldl -pthread -o t && ./t
#include <dlfcn.h>
#include <pthread.h>
#include <unistd.h>

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

using OpenFn = void* (*)(const char*, uint64_t, int);
using CloseFn = void (*)(void*);
using ReconcileFn = int (*)(void*);
using BaseFn = uint64_t (*)(void*);
using CreateFn = int (*)(void*, const uint8_t*, uint64_t, uint64_t*);
using SealFn = int (*)(void*, const uint8_t*);
using GetFn = int (*)(void*, const uint8_t*, int64_t, uint64_t*, uint64_t*);
using ReleaseFn = int (*)(void*, const uint8_t*);
using ContainsFn = int (*)(void*, const uint8_t*);
using DeleteFn = int (*)(void*, const uint8_t*);
using EvictFn = uint64_t (*)(void*, uint64_t);
using StatsFn = void (*)(void*, uint64_t*, uint64_t*, uint64_t*,
                         uint64_t*);

static OpenFn s_open;
static CloseFn s_close;
static ReconcileFn s_reconcile;
static BaseFn s_base;
static CreateFn s_create;
static SealFn s_seal;
static GetFn s_get;
static ReleaseFn s_release;
static ContainsFn s_contains;
static DeleteFn s_delete;
static EvictFn s_evict;
static StatsFn s_stats;

static void make_id(uint8_t* id, int n) {
  std::memset(id, 0, 20);
  std::snprintf(reinterpret_cast<char*>(id), 20, "obj-%d", n);
}

static void put_obj(void* s, int n, const std::string& payload) {
  uint8_t id[20];
  make_id(id, n);
  uint64_t off = 0;
  assert(s_create(s, id, payload.size(), &off) == 0);
  std::memcpy(reinterpret_cast<char*>(s_base(s)) + off, payload.data(),
              payload.size());
  assert(s_seal(s, id) == 0);
}

static std::string get_obj(void* s, int n, int64_t timeout_ms = 1000) {
  uint8_t id[20];
  make_id(id, n);
  uint64_t off = 0, size = 0;
  if (s_get(s, id, timeout_ms, &off, &size) != 0) return "";
  std::string out(reinterpret_cast<char*>(s_base(s)) + off, size);
  s_release(s, id);
  return out;
}

int main(int argc, char** argv) {
  assert(argc >= 3 && "usage: test_shm_store <libshmstore.so> <arena>");
  void* lib = dlopen(argv[1], RTLD_NOW);
  assert(lib && "dlopen failed");
  s_open = reinterpret_cast<OpenFn>(dlsym(lib, "shm_store_open"));
  s_close = reinterpret_cast<CloseFn>(dlsym(lib, "shm_store_close"));
  s_reconcile =
      reinterpret_cast<ReconcileFn>(dlsym(lib, "shm_store_reconcile"));
  s_base = reinterpret_cast<BaseFn>(dlsym(lib, "shm_store_base"));
  s_create = reinterpret_cast<CreateFn>(dlsym(lib, "shm_store_create"));
  s_seal = reinterpret_cast<SealFn>(dlsym(lib, "shm_store_seal"));
  s_get = reinterpret_cast<GetFn>(dlsym(lib, "shm_store_get"));
  s_release = reinterpret_cast<ReleaseFn>(dlsym(lib, "shm_store_release"));
  s_contains = reinterpret_cast<ContainsFn>(dlsym(lib, "shm_store_contains"));
  s_delete = reinterpret_cast<DeleteFn>(dlsym(lib, "shm_store_delete"));
  s_evict = reinterpret_cast<EvictFn>(dlsym(lib, "shm_store_evict"));
  s_stats = reinterpret_cast<StatsFn>(dlsym(lib, "shm_store_stats"));
  assert(s_open && s_create && s_seal && s_get && s_evict);

  const char* arena = argv[2];
  // metadata (client ref ledgers) needs ~26 MB: a too-small arena must
  // fail cleanly, not scribble out of bounds (regression: this test
  // originally segfaulted here)
  assert(s_open(arena, 8ull << 20, 1) == nullptr);
  void* s = s_open(arena, 64ull << 20, 1);  // 64 MB
  assert(s);

  // 1. create/seal/get round trip + contains/delete
  put_obj(s, 1, "hello-shm");
  uint8_t id1[20];
  make_id(id1, 1);
  assert(s_contains(s, id1) == 1);
  assert(get_obj(s, 1) == "hello-shm");
  assert(s_delete(s, id1) == 0);
  assert(s_contains(s, id1) == 0);
  std::printf("roundtrip ok\n");

  // 2. blocking get: reader attaches BEFORE the writer puts
  {
    std::string got;
    std::thread reader([&] { got = get_obj(s, 2, 5000); });
    usleep(50 * 1000);
    put_obj(s, 2, "late-arrival");
    reader.join();
    assert(got == "late-arrival");
  }
  std::printf("blocking get ok\n");

  // 3. concurrent writers: 4 threads x 64 objects, then integrity-check
  {
    std::vector<std::thread> ws;
    for (int t = 0; t < 4; ++t) {
      ws.emplace_back([&, t] {
        for (int i = 0; i < 64; ++i) {
          int n = 1000 + t * 64 + i;
          put_obj(s, n, "payload-" + std::to_string(n));
        }
      });
    }
    for (auto& w : ws) w.join();
    for (int t = 0; t < 4; ++t)
      for (int i = 0; i < 64; ++i) {
        int n = 1000 + t * 64 + i;
        assert(get_obj(s, n) == "payload-" + std::to_string(n));
      }
  }
  std::printf("concurrent writers ok\n");

  // 4. eviction under pressure: unpinned objects make room
  {
    uint64_t used = 0, cap = 0, nobj = 0, nevict = 0;
    s_stats(s, &used, &cap, &nobj, &nevict);
    std::string big(4 * 1024 * 1024, 'x');
    for (int i = 0; i < 64; ++i) put_obj(s, 2000 + i, big);  // > capacity
    s_stats(s, &used, &cap, &nobj, &nevict);
    assert(nevict > 0);
    assert(used <= cap);
    // the newest object must still be present
    assert(get_obj(s, 2063) == big);
  }
  std::printf("eviction ok\n");

  // 5. second attached client sees the same objects zero-copy
  {
    void* s2 = s_open(arena, 0, 0);
    assert(s2);
    put_obj(s, 3, "cross-client");
    uint8_t id[20];
    make_id(id, 3);
    uint64_t off = 0, size = 0;
    assert(s_get(s2, id, 1000, &off, &size) == 0);
    assert(std::string(reinterpret_cast<char*>(s_base(s2)) + off, size) ==
           "cross-client");
    s_release(s2, id);
    s_close(s2);
    // reconcile reclaims the closed client's slot bookkeeping
    s_reconcile(s);
  }
  std::printf("multi-client ok\n");

  s_close(s);
  std::printf("NATIVE_STORE_TESTS_PASS\n");
  return 0;
}
