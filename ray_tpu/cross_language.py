"""Cross-language function registry: call Python by name from C++.

Reference: python/ray/cross_language.py — the reference invokes across
languages through function descriptors (module/class/function names)
rather than pickled code, since the caller can't pickle the callee's
language. Here a Python process registers ``name -> fn`` in the GCS KV;
a C++ ClientSession (cpp/include/ray_tpu/client.h) submits tasks by
name with a bytes payload through the Ray Client server.

Contract: ``fn(payload: bytes) -> bytes`` — byte strings are the only
type both languages agree on without a schema layer.
"""
from __future__ import annotations

from typing import Callable

import cloudpickle

_NS = "crosslang"


def register_function(name: str, fn: Callable[[bytes], bytes]) -> None:
    """Register fn under ``name`` for by-name invocation (any language).
    Must be called from a cluster-connected process."""
    from ._private.core_worker import global_worker

    global_worker().gcs.kv_put(
        ns=_NS, key=name, value=cloudpickle.dumps(fn))


def get_function(name: str) -> Callable[[bytes], bytes]:
    from ._private.core_worker import global_worker

    blob = global_worker().gcs.kv_get(ns=_NS, key=name)
    if blob is None:
        raise KeyError(f"no cross-language function registered as {name!r}")
    return cloudpickle.loads(blob)
