"""Cross-language function registry: call Python by name from C++.

Reference: python/ray/cross_language.py — the reference invokes across
languages through function descriptors (module/class/function names)
rather than pickled code, since the caller can't pickle the callee's
language. Here a Python process registers ``name -> fn`` in the GCS KV;
a C++ ClientSession (cpp/include/ray_tpu/client.h) submits tasks by
name with a bytes payload through the Ray Client server.

Contract: ``fn(payload: bytes) -> bytes`` — byte strings are the only
type both languages agree on without a schema layer.
"""
from __future__ import annotations

from typing import Callable

import cloudpickle

_NS = "crosslang"


def register_function(name: str, fn: Callable[[bytes], bytes]) -> None:
    """Register fn under ``name`` for by-name invocation (any language).
    Must be called from a cluster-connected process."""
    from ._private.core_worker import global_worker

    global_worker().gcs.kv_put(
        ns=_NS, key=name, value=cloudpickle.dumps(fn))


def get_function(name: str) -> Callable[[bytes], bytes]:
    from ._private.core_worker import global_worker

    blob = global_worker().gcs.kv_get(ns=_NS, key=name)
    if blob is None:
        raise KeyError(f"no cross-language function registered as {name!r}")
    return cloudpickle.loads(blob)


# ---------------------------------------------------------------------------
# the reverse direction: Python -> C++ by descriptor
# (reference: cpp/src/ray/runtime/task/task_executor.cc — C++ workers
# register functions and execute pushed tasks; python/ray/cross_language
# .py cpp_function builds the descriptor-call)
# ---------------------------------------------------------------------------
_CPP_NS = "cpp_workers"


def register_cpp_worker(functions, host: str, port: int) -> None:
    """Record a C++ task server's address under each function it
    serves, plus the NODE it registered from. C++ workers usually bind
    loopback and announce through a co-located client server, so the
    registering process's node id lets invocations pin to the right
    node on multi-node clusters. Called by the client server
    (client_register_cpp_worker)."""
    from ._private.core_worker import global_worker

    w = global_worker()
    for name in functions:
        w.gcs.kv_put(ns=_CPP_NS, key=str(name),
                     value=f"{host}:{port}|{w.node_id}".encode())


def _resolve_cpp_worker(name: str):
    from ._private.core_worker import global_worker

    w = global_worker()
    addr = w.gcs.kv_get(ns=_CPP_NS, key=name)
    if addr is None:
        raise KeyError(f"no C++ worker serves function {name!r}")
    rec = addr.decode()
    node_id = None
    if "|" in rec:
        rec, node_id = rec.rsplit("|", 1)
    host, port = rec.rsplit(":", 1)
    return host, int(port), node_id


def invoke_cpp_local(name: str, payload: bytes,
                     timeout: float = 60.0) -> bytes:
    """Execute one C++ function invocation from THIS process: resolve
    the serving worker's address from the registry and push the task
    over the framework's RPC framing (the C++ TaskServer speaks the
    same (seq, method, kwargs) protocol as every other peer)."""
    from ._private.core_worker import global_worker

    w = global_worker()
    host, port, _node = _resolve_cpp_worker(name)
    cli = w._pool.get(host, port)
    out = cli.call_sync("invoke_cpp", fn=name, payload=bytes(payload),
                        timeout=timeout)
    return bytes(out)


_cpp_invoke_task = None


def cpp_function(name: str):
    """A handle to a C++-executed function: ``cpp_function("f").remote(
    payload) -> ObjectRef[bytes]``. The invocation rides a normal task
    (scheduling, retries, ownership) whose executor pushes the payload
    to the registered C++ task server and returns its bytes reply."""
    global _cpp_invoke_task
    if _cpp_invoke_task is None:
        import ray_tpu

        @ray_tpu.remote
        def _call_cpp(fn_name: str, payload: bytes) -> bytes:
            from ray_tpu.cross_language import invoke_cpp_local

            return invoke_cpp_local(fn_name, payload)

        _cpp_invoke_task = _call_cpp

    class _CppFunction:
        def __init__(self, fn_name):
            self._name = fn_name
            self._node_id = None

        def remote(self, payload: bytes):
            # pin the invoke task to the C++ worker's NODE: its server
            # usually binds loopback, reachable only from there
            if self._node_id is None:
                try:
                    _h, _p, self._node_id = _resolve_cpp_worker(
                        self._name)
                except KeyError:
                    self._node_id = ""  # fail inside the task instead
            if self._node_id:
                from .util.scheduling_strategies import (
                    NodeAffinitySchedulingStrategy,
                )

                return _cpp_invoke_task.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        self._node_id)
                ).remote(self._name, bytes(payload))
            return _cpp_invoke_task.remote(self._name, bytes(payload))

        def __repr__(self):
            return f"CppFunction({self._name!r})"

    return _CppFunction(name)


# ---------------------------------------------------------------------------
# C++ ACTORS: stateful native instances hosted by a C++ TaskServer
# (reference: cpp/include/ray/api/actor_handle.h, actor_creator.h —
# RAY_REMOTE actor classes created and called through handles; runtime
# in cpp/src/ray/runtime/task/task_executor.cc). Here creation and every
# method call ride a PYTHON proxy actor pinned to the C++ worker's node:
# the proxy gives the standard actor guarantees (per-caller submission
# ordering, restarts, named handles) while execution is native — the
# C++ server runs one method of an instance at a time under its lock.
# ---------------------------------------------------------------------------


_cpp_proxy_cls = None


def _get_cpp_proxy_cls():
    global _cpp_proxy_cls
    if _cpp_proxy_cls is None:
        import ray_tpu

        @ray_tpu.remote
        class _CppActorProxy:
            def __init__(self, cls_name: str, init_payload: bytes,
                         timeout_s: float = 60.0, host: str = "",
                         port: int = 0):
                import uuid

                from ray_tpu._private.core_worker import global_worker
                from ray_tpu.cross_language import _resolve_cpp_worker

                if host and port:
                    # the creator already resolved the worker (and
                    # pinned this proxy to its node): reuse that
                    # resolution — a second lookup could race to a
                    # DIFFERENT worker serving the same class
                    self._host, self._port = host, int(port)
                else:
                    self._host, self._port, _ = _resolve_cpp_worker(
                        "actor:" + cls_name)
                self._aid = uuid.uuid4().hex
                self._timeout = float(timeout_s)
                w = global_worker()
                w._pool.get(self._host, self._port).call_sync(
                    "create_cpp_actor", cls=cls_name, actor_id=self._aid,
                    payload=bytes(init_payload), timeout=self._timeout)

            def call(self, method: str, payload: bytes = b"",
                     timeout_s=None) -> bytes:
                from ray_tpu._private.core_worker import global_worker

                w = global_worker()
                out = w._pool.get(self._host, self._port).call_sync(
                    "invoke_cpp_actor", actor_id=self._aid,
                    actor_method=str(method), payload=bytes(payload),
                    timeout=float(timeout_s or self._timeout))
                return bytes(out)

            def destroy(self) -> bool:
                from ray_tpu._private.core_worker import global_worker

                w = global_worker()
                w._pool.get(self._host, self._port).call_sync(
                    "destroy_cpp_actor", actor_id=self._aid,
                    timeout=self._timeout)
                return True

        _cpp_proxy_cls = _CppActorProxy
    return _cpp_proxy_cls


class CppActorHandle:
    """Handle to a C++-hosted actor instance. ``call(method, payload)``
    returns an ObjectRef[bytes]; calls from one handle execute in
    submission order (proxy actor max_concurrency=1 + per-instance lock
    on the C++ side)."""

    def __init__(self, proxy):
        self._proxy = proxy

    def call(self, method: str, payload: bytes = b"", timeout_s=None):
        return self._proxy.call.remote(method, payload, timeout_s)

    def destroy(self):
        import ray_tpu

        ray_tpu.get(self._proxy.destroy.remote(), timeout=60)
        ray_tpu.kill(self._proxy)


def cpp_actor_class(cls_name: str):
    """Factory for C++ actor instances: ``cpp_actor_class("Counter")
    .remote(init_payload)`` creates the native instance on the node
    whose TaskServer registered the class, and returns a
    :class:`CppActorHandle`."""

    class _CppActorClass:
        @staticmethod
        def remote(init_payload: bytes = b"",
                   timeout_s: float = 60.0) -> CppActorHandle:
            """``timeout_s``: default RPC timeout for create/call/destroy
            (long-running native methods should raise it; per-call
            override via ``handle.call(..., timeout_s=...)``)."""
            host, port, node_id = _resolve_cpp_worker(
                "actor:" + cls_name)
            proxy_cls = _get_cpp_proxy_cls()
            opts = {"max_concurrency": 1}
            if node_id:
                from .util.scheduling_strategies import (
                    NodeAffinitySchedulingStrategy,
                )

                opts["scheduling_strategy"] = (
                    NodeAffinitySchedulingStrategy(node_id))
            proxy = proxy_cls.options(**opts).remote(
                cls_name, bytes(init_payload), timeout_s, host, port)
            return CppActorHandle(proxy)

        def __repr__(self):
            return f"CppActorClass({cls_name!r})"

    return _CppActorClass()
