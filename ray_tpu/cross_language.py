"""Cross-language function registry: call Python by name from C++.

Reference: python/ray/cross_language.py — the reference invokes across
languages through function descriptors (module/class/function names)
rather than pickled code, since the caller can't pickle the callee's
language. Here a Python process registers ``name -> fn`` in the GCS KV;
a C++ ClientSession (cpp/include/ray_tpu/client.h) submits tasks by
name with a bytes payload through the Ray Client server.

Contract: ``fn(payload: bytes) -> bytes`` — byte strings are the only
type both languages agree on without a schema layer.
"""
from __future__ import annotations

from typing import Callable

import cloudpickle

_NS = "crosslang"


def register_function(name: str, fn: Callable[[bytes], bytes]) -> None:
    """Register fn under ``name`` for by-name invocation (any language).
    Must be called from a cluster-connected process."""
    from ._private.core_worker import global_worker

    global_worker().gcs.kv_put(
        ns=_NS, key=name, value=cloudpickle.dumps(fn))


def get_function(name: str) -> Callable[[bytes], bytes]:
    from ._private.core_worker import global_worker

    blob = global_worker().gcs.kv_get(ns=_NS, key=name)
    if blob is None:
        raise KeyError(f"no cross-language function registered as {name!r}")
    return cloudpickle.loads(blob)


# ---------------------------------------------------------------------------
# the reverse direction: Python -> C++ by descriptor
# (reference: cpp/src/ray/runtime/task/task_executor.cc — C++ workers
# register functions and execute pushed tasks; python/ray/cross_language
# .py cpp_function builds the descriptor-call)
# ---------------------------------------------------------------------------
_CPP_NS = "cpp_workers"


def register_cpp_worker(functions, host: str, port: int) -> None:
    """Record a C++ task server's address under each function it
    serves, plus the NODE it registered from. C++ workers usually bind
    loopback and announce through a co-located client server, so the
    registering process's node id lets invocations pin to the right
    node on multi-node clusters. Called by the client server
    (client_register_cpp_worker)."""
    from ._private.core_worker import global_worker

    w = global_worker()
    for name in functions:
        w.gcs.kv_put(ns=_CPP_NS, key=str(name),
                     value=f"{host}:{port}|{w.node_id}".encode())


def _resolve_cpp_worker(name: str):
    from ._private.core_worker import global_worker

    w = global_worker()
    addr = w.gcs.kv_get(ns=_CPP_NS, key=name)
    if addr is None:
        raise KeyError(f"no C++ worker serves function {name!r}")
    rec = addr.decode()
    node_id = None
    if "|" in rec:
        rec, node_id = rec.rsplit("|", 1)
    host, port = rec.rsplit(":", 1)
    return host, int(port), node_id


def invoke_cpp_local(name: str, payload: bytes,
                     timeout: float = 60.0) -> bytes:
    """Execute one C++ function invocation from THIS process: resolve
    the serving worker's address from the registry and push the task
    over the framework's RPC framing (the C++ TaskServer speaks the
    same (seq, method, kwargs) protocol as every other peer)."""
    from ._private.core_worker import global_worker

    w = global_worker()
    host, port, _node = _resolve_cpp_worker(name)
    cli = w._pool.get(host, port)
    out = cli.call_sync("invoke_cpp", fn=name, payload=bytes(payload),
                        timeout=timeout)
    return bytes(out)


_cpp_invoke_task = None


def cpp_function(name: str):
    """A handle to a C++-executed function: ``cpp_function("f").remote(
    payload) -> ObjectRef[bytes]``. The invocation rides a normal task
    (scheduling, retries, ownership) whose executor pushes the payload
    to the registered C++ task server and returns its bytes reply."""
    global _cpp_invoke_task
    if _cpp_invoke_task is None:
        import ray_tpu

        @ray_tpu.remote
        def _call_cpp(fn_name: str, payload: bytes) -> bytes:
            from ray_tpu.cross_language import invoke_cpp_local

            return invoke_cpp_local(fn_name, payload)

        _cpp_invoke_task = _call_cpp

    class _CppFunction:
        def __init__(self, fn_name):
            self._name = fn_name
            self._node_id = None

        def remote(self, payload: bytes):
            # pin the invoke task to the C++ worker's NODE: its server
            # usually binds loopback, reachable only from there
            if self._node_id is None:
                try:
                    _h, _p, self._node_id = _resolve_cpp_worker(
                        self._name)
                except KeyError:
                    self._node_id = ""  # fail inside the task instead
            if self._node_id:
                from .util.scheduling_strategies import (
                    NodeAffinitySchedulingStrategy,
                )

                return _cpp_invoke_task.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        self._node_id)
                ).remote(self._name, bytes(payload))
            return _cpp_invoke_task.remote(self._name, bytes(payload))

        def __repr__(self):
            return f"CppFunction({self._name!r})"

    return _CppFunction(name)
