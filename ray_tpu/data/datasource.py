"""Datasources: read_* / from_* / write_* (reference: data/read_api.py +
datasource/).

Read functions build Read logical ops whose read tasks run remotely and
return blocks; file formats ride pyarrow.
"""
from __future__ import annotations

import glob as globlib
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from .block import rows_to_block
from .context import DataContext
from .dataset import Dataset
from .plan import InputBlocks, LogicalPlan, Read


def _make_dataset(read_tasks, name) -> Dataset:
    return Dataset(LogicalPlan([Read(name=name, read_tasks=read_tasks)]))


import builtins as _builtins

builtins_range = _builtins.range


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    par = parallelism if parallelism > 0 else (
        DataContext.get_current().default_read_parallelism
    )
    par = max(1, min(par, n)) if n else 1
    bounds = [(n * i // par, n * (i + 1) // par) for i in builtins_range(par)]

    def make_task(lo, hi):
        def task():
            return [rows_to_block([{"id": i} for i in builtins_range(lo, hi)])]

        return task

    return _make_dataset(
        [make_task(lo, hi) for lo, hi in bounds], f"Range[{n}]"
    )


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    par = parallelism if parallelism > 0 else (
        DataContext.get_current().default_read_parallelism
    )
    par = max(1, min(par, len(items) or 1))
    n = len(items)
    # contiguous chunks: row order must be preserved (same as range())
    bounds = [(n * i // par, n * (i + 1) // par) for i in builtins_range(par)]
    rows_chunks = [
        [
            it if isinstance(it, dict) else {"item": it}
            for it in items[lo:hi]
        ]
        for lo, hi in bounds
    ]

    def make_task(rows):
        def task():
            return [rows_to_block(rows)]

        return task

    return _make_dataset(
        [make_task(rows) for rows in rows_chunks if rows] or
        [make_task([])],
        f"FromItems[{len(items)}]",
    )


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return Dataset(LogicalPlan([InputBlocks(name="FromPandas",
                                            blocks=[table])]))


def from_arrow(table) -> Dataset:
    return Dataset(LogicalPlan([InputBlocks(name="FromArrow",
                                            blocks=[table])]))


def from_numpy(arr: np.ndarray) -> Dataset:
    rows = [{"data": row} for row in arr]
    return from_items(rows)


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(
                    os.path.join(root, f)
                    for f in files
                    if suffix is None or f.endswith(suffix)
                )
        elif any(ch in p for ch in "*?["):
            out.extend(globlib.glob(p))
        else:
            out.append(p)
    return sorted(out)


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths, ".parquet")

    def make_task(path):
        def task():
            import pyarrow.parquet as pq

            return [pq.read_table(path, columns=columns)]

        return task

    return _make_dataset([make_task(f) for f in files],
                         f"ReadParquet[{len(files)}]")


def read_csv(paths, **csv_opts) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def make_task(path):
        def task():
            import pyarrow.csv as pacsv

            return [pacsv.read_csv(path)]

        return task

    return _make_dataset([make_task(f) for f in files],
                         f"ReadCSV[{len(files)}]")


def read_json(paths) -> Dataset:
    files = _expand_paths(paths, None)

    def make_task(path):
        def task():
            import pyarrow.json as pajson

            return [pajson.read_json(path)]

        return task

    return _make_dataset([make_task(f) for f in files],
                         f"ReadJSON[{len(files)}]")


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths, None)

    def make_task(path):
        def task():
            with open(path, "rb") as f:
                return [rows_to_block([{"path": path, "bytes": f.read()}])]

        return task

    return _make_dataset([make_task(f) for f in files],
                         f"ReadBinary[{len(files)}]")


def read_text(paths) -> Dataset:
    files = _expand_paths(paths, None)

    def make_task(path):
        def task():
            with open(path) as f:
                return [rows_to_block([{"text": line.rstrip("\n")}
                                       for line in f])]

        return task

    return _make_dataset([make_task(f) for f in files],
                         f"ReadText[{len(files)}]")


# ---------------------------------------------------------------------------
def write_blocks(ds: Dataset, path: str, fmt: str):
    import ray_tpu as ray

    from .block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    for i, (ref, _meta) in enumerate(ds.iter_internal_refs()):
        block = ray.get(ref, timeout=600)
        acc = BlockAccessor.for_block(block)
        fname = os.path.join(path, f"part-{i:05d}.{fmt}")
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(acc.to_arrow(), fname)
        elif fmt == "csv":
            import pyarrow.csv as pacsv

            pacsv.write_csv(acc.to_arrow(), fname)
        elif fmt == "json":
            acc.to_pandas().to_json(fname, orient="records", lines=True)
        else:
            raise ValueError(fmt)
