"""Streaming executor: logical plan -> bounded-in-flight task pipeline.

Reference: python/ray/data/_internal/execution/streaming_executor.py:53 — a
pull-based operator DAG with backpressure. Here each stage is a generator
of (block_ref, meta) pairs; map stages keep at most
DataContext.max_tasks_in_flight tasks outstanding (the backpressure), and
all-to-all stages form a barrier (as in the reference's exchange planner,
planner/exchange/).

Blocks live in the shm object store between stages; metadata (row count /
byte size) returns inline so the driver can plan limits/splits without
fetching data.
"""
from __future__ import annotations

import collections
import random
from typing import Any, Iterator, List, Optional, Tuple

import ray_tpu as ray

from .block import BlockAccessor, rows_to_block
from .context import DataContext
from .plan import (
    AllToAll, InputBlocks, Join, Limit, LogicalPlan, MapBlocks, Read,
    Union, Zip,
)

Meta = dict
RefMeta = Tuple[Any, Meta]  # (ObjectRef -> Block, metadata)


def _meta_of(block) -> Meta:
    acc = BlockAccessor.for_block(block)
    return {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


# --- remote task bodies -----------------------------------------------------
def _run_read_task(read_task):
    blocks = read_task()
    out = []
    for b in blocks:
        out.append((ray.put(b), _meta_of(b)))
    return out


def _run_map_task(fn, block):
    blocks = fn(block)
    return [(ray.put(b), _meta_of(b)) for b in blocks]


class _MapWorker:
    """Actor for stateful (class) UDFs — reference: ActorPoolMapOperator."""

    def __init__(self, cls, args):
        self.udf = cls(*args)

    def apply(self, fn, block):
        blocks = fn(self.udf, block)
        return [(ray.put(b), _meta_of(b)) for b in blocks]


class StreamingExecutor:
    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()

    # ------------------------------------------------------------------
    def execute(self, plan: LogicalPlan) -> Iterator[RefMeta]:
        stream: Iterator[RefMeta] = iter(())
        for op in plan.optimized().ops:
            if isinstance(op, Read):
                stream = self._read_stage(op)
            elif isinstance(op, InputBlocks):
                stream = self._input_stage(op)
            elif isinstance(op, MapBlocks):
                if op.actor_cls is not None:
                    stream = self._actor_map_stage(op, stream)
                else:
                    stream = self._map_stage(op, stream)
            elif isinstance(op, Limit):
                stream = self._limit_stage(op, stream)
            elif isinstance(op, AllToAll):
                stream = self._all_to_all_stage(op, stream)
            elif isinstance(op, Union):
                stream = self._union_stage(op, stream)
            elif isinstance(op, Join):
                stream = self._join_stage(op, stream)
            elif isinstance(op, Zip):
                stream = self._zip_stage(op, stream)
            else:
                raise TypeError(f"unknown logical op {op}")
        return stream

    # ------------------------------------------------------------------
    def _input_stage(self, op: InputBlocks) -> Iterator[RefMeta]:
        for entry in op.blocks:
            if isinstance(entry, tuple):
                yield entry
            else:
                yield (ray.put(entry), _meta_of(entry))

    def _read_stage(self, op: Read) -> Iterator[RefMeta]:
        remote_read = ray.remote(_run_read_task)
        window = collections.deque()
        for task in op.read_tasks:
            window.append(remote_read.remote(task))
            if len(window) >= self.ctx.max_tasks_in_flight:
                yield from ray.get(window.popleft(), timeout=600)
        while window:
            yield from ray.get(window.popleft(), timeout=600)

    def _map_stage(self, op: MapBlocks, upstream) -> Iterator[RefMeta]:
        remote_map = ray.remote(_run_map_task)
        window = collections.deque()
        for ref, meta in upstream:
            window.append(remote_map.remote(op.fn, ref))
            if len(window) >= self.ctx.max_tasks_in_flight:
                yield from ray.get(window.popleft(), timeout=600)
        while window:
            yield from ray.get(window.popleft(), timeout=600)

    def _actor_map_stage(self, op: MapBlocks, upstream) -> Iterator[RefMeta]:
        Worker = ray.remote(_MapWorker)
        pool = [
            Worker.remote(op.actor_cls, op.fn_args)
            for _ in range(op.actor_pool_size)
        ]
        try:
            window = collections.deque()
            i = 0
            for ref, meta in upstream:
                actor = pool[i % len(pool)]
                i += 1
                window.append(actor.apply.remote(op.fn, ref))
                if len(window) >= self.ctx.max_tasks_in_flight:
                    yield from ray.get(window.popleft(), timeout=600)
            while window:
                yield from ray.get(window.popleft(), timeout=600)
        finally:
            for a in pool:
                try:
                    ray.kill(a)
                except Exception:
                    pass

    def _limit_stage(self, op: Limit, upstream) -> Iterator[RefMeta]:
        remaining = op.n
        for ref, meta in upstream:
            if remaining <= 0:
                break
            rows = meta["num_rows"]
            if rows <= remaining:
                remaining -= rows
                yield ref, meta
            else:
                block = ray.get(ref, timeout=600)
                cut = BlockAccessor.for_block(block).slice(0, remaining)
                remaining = 0
                yield ray.put(cut), _meta_of(cut)

    def _union_stage(self, op: Union, upstream) -> Iterator[RefMeta]:
        yield from upstream
        for other in op.others:
            yield from self.execute(other)

    def _join_stage(self, op: Join, upstream) -> Iterator[RefMeta]:
        """Hash join: both sides shard by crc32(key) into P partitions,
        one join task per partition (reference: hash-shuffle join,
        data/_internal/execution/operators/join.py)."""
        left = list(upstream)
        right = list(self.execute(op.other))
        P = max(1, min(max(len(left), len(right)), 8))
        key, how, suffix = op.on, op.how, op.right_suffix

        def shard_task(block, n):
            import zlib

            shards: List[List[Any]] = [[] for _ in range(n)]
            for r in BlockAccessor.for_block(block).iter_rows():
                h = zlib.crc32(repr(r[key]).encode())
                shards[h % n].append(r)
            return [
                (lambda b: (ray.put(b), _meta_of(b)))(rows_to_block(s))
                for s in shards
            ]

        shard = ray.remote(shard_task)
        # submit the whole map side first, THEN gather: the shard tasks
        # run in parallel across the cluster
        left_futs = [shard.remote(ref, P) for ref, _m in left]
        right_futs = [shard.remote(ref, P) for ref, _m in right]
        left_parts = [ray.get(f, timeout=600) for f in left_futs]
        right_parts = [ray.get(f, timeout=600) for f in right_futs]

        def join_task(n_left, *shards):
            build: dict = {}
            for s in shards[n_left:]:
                for r in BlockAccessor.for_block(s).iter_rows():
                    build.setdefault(r[key], []).append(r)
            out = []
            for s in shards[:n_left]:
                for l in BlockAccessor.for_block(s).iter_rows():
                    matches = build.get(l[key], ())
                    if matches:
                        for r in matches:
                            row = dict(l)
                            for ck, cv in r.items():
                                if ck == key:
                                    continue
                                row[ck + suffix if ck in row else ck] = cv
                            out.append(row)
                    elif how == "left":
                        out.append(dict(l))
            b = rows_to_block(out)
            return (ray.put(b), _meta_of(b))

        join = ray.remote(join_task)
        futures = []
        for p in range(P):
            l_shards = [parts[p][0] for parts in left_parts]
            r_shards = [parts[p][0] for parts in right_parts]
            futures.append(
                join.remote(len(l_shards), *l_shards, *r_shards))
        for fut in futures:
            yield ray.get(fut)

    def _zip_stage(self, op: Zip, upstream) -> Iterator[RefMeta]:
        """Positional zip: pairs the i-th row of each side (row counts
        must match). Runs as one task over the collected blocks —
        correctness first; blockwise alignment is an optimization the
        reference also only applies when block shapes already agree."""
        left = [ref for ref, _m in upstream]
        right = [ref for ref, _m in self.execute(op.other)]

        def zip_task(n_left, *blocks):
            def rows(bs):
                for b in bs:
                    yield from BlockAccessor.for_block(b).iter_rows()

            sentinel = object()
            out = []
            li, ri = rows(blocks[:n_left]), rows(blocks[n_left:])
            while True:
                l = next(li, sentinel)
                r = next(ri, sentinel)
                if l is sentinel and r is sentinel:
                    break
                if l is sentinel or r is sentinel:
                    # row-count mismatch is a user error, not silent
                    # truncation
                    side = "right" if l is sentinel else "left"
                    raise ValueError(f"zip: {side} side has more rows")
                row = dict(l)
                for ck, cv in r.items():
                    row[ck + "_1" if ck in row else ck] = cv
                out.append(row)
            b = rows_to_block(out)
            return (ray.put(b), _meta_of(b))

        fut = ray.remote(zip_task).remote(len(left), *left, *right)
        yield ray.get(fut)

    # ------------------------------------------------------------------
    # all-to-all exchanges (barrier; reference: planner/exchange/)
    # ------------------------------------------------------------------
    def _all_to_all_stage(self, op: AllToAll, upstream) -> Iterator[RefMeta]:
        inputs = list(upstream)
        if op.kind == "repartition":
            yield from self._repartition(inputs, op.params["num_blocks"])
        elif op.kind == "random_shuffle":
            yield from self._random_shuffle(inputs, op.params.get("seed"))
        elif op.kind == "sort":
            yield from self._sort(inputs, op.params["key"],
                                  op.params.get("descending", False))
        elif op.kind == "groupby":
            yield from self._groupby(inputs, op.params)
        else:
            raise ValueError(f"unknown exchange {op.kind}")

    def _repartition(self, inputs: List[RefMeta], n: int):
        """Plan contiguous row segments into n equal outputs, then build
        each output with one remote task (slice + combine)."""
        total = sum(m["num_rows"] for _, m in inputs)
        sizes = [total // n + (1 if i < total % n else 0) for i in range(n)]
        assignments: List[List[Tuple[Any, int, int]]] = [[] for _ in range(n)]
        out_i = 0
        out_room = sizes[0] if n else 0
        for ref, meta in inputs:
            pos, rows = 0, meta["num_rows"]
            while rows > 0:
                while out_room == 0 and out_i < n - 1:
                    out_i += 1
                    out_room = sizes[out_i]
                take = rows if out_i == n - 1 else min(rows, out_room)
                assignments[out_i].append((ref, pos, pos + take))
                pos += take
                rows -= take
                out_room -= take

        def build_task(segments):
            pieces = []
            for ref, start, end in segments:
                block = ray.get(ref, timeout=600)
                pieces.append(
                    BlockAccessor.for_block(block).slice(start, end)
                )
            merged = (
                BlockAccessor.combine(pieces) if pieces else rows_to_block([])
            )
            return [(ray.put(merged), _meta_of(merged))]

        remote_build = ray.remote(build_task)
        outs = ray.get(
            [remote_build.remote(seg) for seg in assignments], timeout=600
        )
        for out in outs:
            yield from out

    def _streaming_exchange(self, inputs: List[RefMeta], shard_fn,
                            finalize_fn, n_out: int):
        """Push-based exchange (reference:
        planner/exchange/push_based_shuffle_task_scheduler.py:415):
        mappers run in bounded waves; as EACH mapper finishes, its
        per-partition shards merge into that partition's running
        accumulator and the consumed shard refs drop immediately — the
        reducers never wait behind a full map barrier, and the peak
        working set is O(wave + accumulators) instead of every map
        output materialized at once (which bounded the old barrier
        exchange by one stage's worth of shm)."""
        import os as _os

        if _os.environ.get("RAY_TPU_DATA_BARRIER_EXCHANGE") == "1":
            # reference-style full-barrier exchange, kept for A/B
            # comparison (tests measure its peak arena usage against
            # the streaming path's)
            yield from self._barrier_exchange(
                inputs, shard_fn, finalize_fn, n_out)
            return
        ctx = DataContext.get_current()
        wave = max(2, ctx.max_tasks_in_flight)
        K = 8  # shards per tree-merge node

        def merge_many(*blocks):
            rows: List[Any] = []
            for b in blocks:
                rows.extend(BlockAccessor.for_block(b).iter_rows())
            return rows_to_block(rows)

        remote_shard = ray.remote(shard_fn)
        remote_merge = ray.remote(merge_many)
        # per-partition pending shards, merged K-at-a-time into a tree
        # (chained pairwise accumulation would COPY the whole partition
        # every round: O(dataset x mappers) shm churn and a
        # multi-generation peak that blows the arena)
        parts: List[List[Any]] = [[] for _ in range(n_out)]
        pending = collections.deque(range(len(inputs)))
        inflight: List[Any] = []  # shard-task "done" markers
        shard_refs_of: dict = {}  # marker -> (input index, shard refs)
        merges_inflight: List[Any] = []

        def _compact(j: int):
            merged = remote_merge.remote(*parts[j])
            parts[j] = [merged]
            merges_inflight.append(merged)
            # bound outstanding merge work: shard tasks must not race
            # ahead of the reducers and pile shards up in shm
            while len(merges_inflight) > wave:
                oldest = merges_inflight.pop(0)
                ready, _ = ray.wait([oldest], num_returns=1, timeout=600)
                if not ready:
                    raise TimeoutError(
                        "exchange merge task made no progress in 600s")

        stalls = 0
        while pending or inflight:
            while pending and len(inflight) < wave:
                i = pending.popleft()
                ref, _meta = inputs[i]
                # one return object PER PARTITION: each shard is
                # independently mergeable (and freeable)
                refs = remote_shard.options(
                    num_returns=n_out).remote(ref, i)
                if n_out == 1:
                    refs = [refs]
                marker = refs[0]
                shard_refs_of[marker] = (i, refs)
                inflight.append(marker)
            done, inflight = ray.wait(inflight, num_returns=1,
                                      timeout=600)
            if not done:
                stalls += 1
                if stalls >= 2:  # a silent-spin loop would hang forever
                    raise TimeoutError(
                        "exchange shard tasks made no progress in 1200s")
                continue
            stalls = 0
            for marker in done:
                i, refs = shard_refs_of.pop(marker)
                # the input block is fully sharded: CONSUME the caller's
                # ref so its shm frees now, not at stage end (the input
                # list is owned by this exchange)
                inputs[i] = None
                for j in range(n_out):
                    parts[j].append(refs[j])
                    if len(parts[j]) >= K:
                        _compact(j)
                # dropping the shard refs leaves the merge tasks' arg
                # retention as their only anchor: freed on consumption
                del refs

        remote_finalize = ray.remote(finalize_fn)
        final_refs = [remote_finalize.remote(j, *parts[j])
                      for j in range(n_out)]
        del parts
        for out in ray.get(final_refs, timeout=600):
            yield from out

    def _barrier_exchange(self, inputs: List[RefMeta], shard_fn,
                          finalize_fn, n_out: int):
        """Full-barrier exchange: every map output materialized before
        any reduce starts (the pre-push design; peak arena usage =
        inputs + ALL shards + outputs)."""
        remote_shard = ray.remote(shard_fn)
        all_refs = []
        for i, (ref, _meta) in enumerate(inputs):
            refs = remote_shard.options(num_returns=n_out).remote(ref, i)
            all_refs.append([refs] if n_out == 1 else refs)
        # barrier: wait for the whole map side
        flat = [r for refs in all_refs for r in refs]
        ray.wait(flat, num_returns=len(flat), timeout=600)
        remote_finalize = ray.remote(finalize_fn)
        final_refs = [
            remote_finalize.remote(j, *[refs[j] for refs in all_refs])
            for j in range(n_out)
        ]
        for out in ray.get(final_refs, timeout=600):
            yield from out

    def _random_shuffle(self, inputs: List[RefMeta], seed):
        n_out = max(1, len(inputs))
        # seeds are drawn in the DRIVER and close over the task fns: a
        # retried/lineage-reconstructed mapper must partition rows
        # EXACTLY like its first run, or rebuilt shards would overlap
        # the already-merged ones (duplicated + dropped rows)
        map_seeds = [
            (seed * 1000 + i if seed is not None
             else random.randrange(1 << 30))
            for i in range(len(inputs))
        ]
        out_seeds = [
            (seed * 7919 + j if seed is not None
             else random.randrange(1 << 30))
            for j in range(n_out)
        ]

        def shard_fn(block, i):
            rng = random.Random(map_seeds[i])
            shards: List[List[Any]] = [[] for _ in range(n_out)]
            for r in BlockAccessor.for_block(block).iter_rows():
                shards[rng.randrange(n_out)].append(r)
            out = tuple(rows_to_block(s) for s in shards)
            return out if n_out > 1 else out[0]

        def finalize_fn(j, *blocks):
            rows: List[Any] = []
            for b in blocks:
                rows.extend(BlockAccessor.for_block(b).iter_rows())
            rng = random.Random(out_seeds[j])
            rng.shuffle(rows)
            b = rows_to_block(rows)
            return [(ray.put(b), _meta_of(b))]

        yield from self._streaming_exchange(
            inputs, shard_fn, finalize_fn, n_out)

    def _sort(self, inputs: List[RefMeta], key, descending: bool):
        # sample boundaries -> range partition -> per-partition sort
        # (reference: sort.py push-based exchange)
        n_out = max(1, len(inputs))

        def sample_task(block):
            rows = list(BlockAccessor.for_block(block).iter_rows())
            k = min(len(rows), 20)
            return [r[key] if isinstance(r, dict) else r
                    for r in random.sample(rows, k)] if rows else []

        samples: List[Any] = []
        for s in ray.get(
            [ray.remote(sample_task).remote(ref) for ref, _ in inputs],
            timeout=600,
        ):
            samples.extend(s)
        samples.sort()
        bounds = [
            samples[int(len(samples) * (i + 1) / n_out)]
            for i in range(n_out - 1)
        ] if samples else []

        def shard_fn(block, _i):
            import bisect

            shards: List[List[Any]] = [[] for _ in range(n_out)]
            for r in BlockAccessor.for_block(block).iter_rows():
                v = r[key] if isinstance(r, dict) else r
                shards[bisect.bisect_left(bounds, v)].append(r)
            out = tuple(rows_to_block(s) for s in shards)
            return out if n_out > 1 else out[0]

        def finalize_fn(_j, *blocks):
            rows: List[Any] = []
            for b in blocks:
                rows.extend(BlockAccessor.for_block(b).iter_rows())
            rows.sort(
                key=(lambda r: r[key] if isinstance(r, dict) else r),
                reverse=descending,
            )
            b = rows_to_block(rows)
            return [(ray.put(b), _meta_of(b))]

        # push-based range exchange; partitions stream through the same
        # merge pipeline as shuffle, then emit in key order
        outs = list(self._streaming_exchange(
            inputs, shard_fn, finalize_fn, n_out))
        yield from (reversed(outs) if descending else outs)

    def _groupby(self, inputs: List[RefMeta], params):
        key = params["key"]
        aggs = params["aggs"]  # list of (name, col, fn) with fn in sum/count/min/max/mean
        n_out = max(1, min(len(inputs), 8))

        def shard_task(block, n):
            import zlib

            shards: List[List[Any]] = [[] for _ in range(n)]
            for r in BlockAccessor.for_block(block).iter_rows():
                # stable across processes (builtin hash() is salted per
                # process for str/bytes — would split groups silently)
                h = zlib.crc32(repr(r[key]).encode())
                shards[h % n].append(r)
            return [
                (lambda b: (ray.put(b), _meta_of(b)))(rows_to_block(s))
                for s in shards
            ]

        def agg_task(*shards):
            groups: dict = {}
            for s in shards:
                for r in BlockAccessor.for_block(s).iter_rows():
                    groups.setdefault(r[key], []).append(r)
            out_rows = []
            for gkey in sorted(groups, key=repr):
                rows = groups[gkey]
                row = {key: gkey}
                for name, col, fn in aggs:
                    if fn == "count":
                        row[name] = len(rows)
                    else:
                        vals = [r[col] for r in rows]
                        mean = sum(vals) / len(vals)
                        row[name] = {
                            "sum": sum(vals),
                            "min": min(vals),
                            "max": max(vals),
                            "mean": mean,
                            # sample std (ddof=1), matching the
                            # reference Dataset API default
                            "std": (
                                (sum((v - mean) ** 2 for v in vals)
                                 / (len(vals) - 1)) ** 0.5
                                if len(vals) > 1 else 0.0
                            ),
                        }[fn]
                out_rows.append(row)
            b = rows_to_block(out_rows)
            return [(ray.put(b), _meta_of(b))]

        shard_lists = ray.get(
            [
                ray.remote(shard_task).remote(ref, n_out)
                for ref, _ in inputs
            ],
            timeout=600,
        )
        for j in range(n_out):
            shards_j = [sl[j][0] for sl in shard_lists]
            yield from ray.get(
                ray.remote(agg_task).remote(*shards_j), timeout=600
            )
