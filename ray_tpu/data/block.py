"""Blocks: the unit of data movement (reference: python/ray/data/block.py,
_internal/arrow_block.py, pandas_block.py).

A block is a pyarrow.Table (columnar, zero-copy through the shm object
store) — or a plain Python list for simple/object rows. BlockAccessor
normalizes both.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None

Block = Union["pa.Table", List[Any]]


def _is_table(block) -> bool:
    return pa is not None and isinstance(block, pa.Table)


class BlockAccessor:
    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if _is_table(self._block):
            return self._block.num_rows
        return len(self._block)

    def size_bytes(self) -> int:
        if _is_table(self._block):
            return self._block.nbytes
        import sys

        return sum(sys.getsizeof(r) for r in self._block)

    def schema(self):
        if _is_table(self._block):
            return self._block.schema
        if self._block:
            first = self._block[0]
            if isinstance(first, dict):
                return {k: type(v).__name__ for k, v in first.items()}
            return type(first).__name__
        return None

    def slice(self, start: int, end: int) -> Block:
        if _is_table(self._block):
            return self._block.slice(start, end - start)
        return self._block[start:end]

    def iter_rows(self) -> Iterable[Any]:
        if _is_table(self._block):
            for batch in self._block.to_batches():
                cols = batch.to_pydict()
                keys = list(cols)
                for i in range(batch.num_rows):
                    yield {k: cols[k][i] for k in keys}
        else:
            yield from self._block

    def to_pandas(self):
        import pandas as pd

        if _is_table(self._block):
            return self._block.to_pandas()
        rows = list(self._block)
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"item": rows})

    def to_arrow(self) -> "pa.Table":
        if _is_table(self._block):
            return self._block
        return rows_to_block(list(self._block), prefer_arrow=True)

    def to_numpy(self, column: Optional[str] = None):
        if _is_table(self._block):
            if column is not None:
                return self._block.column(column).to_numpy(
                    zero_copy_only=False
                )
            return {
                name: self._block.column(name).to_numpy(zero_copy_only=False)
                for name in self._block.column_names
            }
        rows = list(self._block)
        if rows and isinstance(rows[0], dict):
            if column is not None:
                return np.asarray([r[column] for r in rows])
            return {
                k: np.asarray([r[k] for r in rows]) for k in rows[0].keys()
            }
        return np.asarray(rows)

    def to_batch_format(self, batch_format: str):
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        if batch_format == "rows":
            return list(self.iter_rows())
        raise ValueError(f"unknown batch_format {batch_format!r}")

    @staticmethod
    def combine(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0] or (
            blocks[:1]
        )
        if not blocks:
            return []
        if all(_is_table(b) for b in blocks):
            return pa.concat_tables(blocks, promote_options="default")
        out: List[Any] = []
        for b in blocks:
            out.extend(BlockAccessor(b).iter_rows())
        return out


def rows_to_block(rows: List[Any], prefer_arrow: bool = True) -> Block:
    """Build a block from Python rows (dicts become arrow when possible)."""
    if (
        prefer_arrow
        and pa is not None
        and rows
        and all(isinstance(r, dict) for r in rows)
    ):
        try:
            return pa.Table.from_pylist(rows)
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            pass
    return list(rows)


def batch_to_block(batch: Any) -> Block:
    """Normalize a user map_batches return value into a block."""
    import sys

    if _is_table(batch):
        return batch
    pd = sys.modules.get("pandas")  # only loaded if the user produced a df
    if pd is not None and isinstance(batch, pd.DataFrame):
        return pa.Table.from_pandas(batch, preserve_index=False) if pa else batch
    if isinstance(batch, dict):
        # dict of column -> array
        if pa is not None:
            try:
                return pa.Table.from_pydict(
                    {
                        k: (v.tolist() if isinstance(v, np.ndarray) and v.ndim > 1 else v)
                        for k, v in batch.items()
                    }
                )
            except Exception:
                pass
        n = len(next(iter(batch.values())))
        return [
            {k: batch[k][i] for k in batch} for i in range(n)
        ]
    if isinstance(batch, list):
        return rows_to_block(batch)
    if isinstance(batch, np.ndarray):
        return rows_to_block([{"data": row} for row in batch])
    raise TypeError(f"cannot convert {type(batch)} to a block")
