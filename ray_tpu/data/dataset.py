"""Dataset: the lazy user-facing API.

Reference: python/ray/data/dataset.py:162 — every method appends a logical
op (map_batches :451, iter_batches :4710, materialize :5672); execution is
deferred to the streaming executor. streaming_split feeds per-host Train
ingest (reference: _internal/split.py).
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union as TUnion

import numpy as np

import ray_tpu as ray

from .block import BlockAccessor, batch_to_block, rows_to_block
from .context import DataContext
from .executor import StreamingExecutor, _meta_of
from .plan import (
    AllToAll, InputBlocks, Join, Limit, LogicalPlan, MapBlocks, Read,
    Union, Zip,
)


def _batch_transform(fn, batch_format, batch_size):
    """Wrap a user batch fn into a block->blocks transform."""

    def transform(block):
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        if n == 0:
            return [rows_to_block([])]  # never call the UDF on empty input
        out = []
        step = batch_size or n
        for start in range(0, n, step):
            piece = acc.slice(start, min(start + step, n))
            batch = BlockAccessor.for_block(piece).to_batch_format(
                batch_format
            )
            out.append(batch_to_block(fn(batch)))
        return out

    return transform


def _row_transform(kind: str, fn):
    def transform(block):
        rows_out: List[Any] = []
        for row in BlockAccessor.for_block(block).iter_rows():
            if kind == "map":
                rows_out.append(fn(row))
            elif kind == "filter":
                if fn(row):
                    rows_out.append(row)
            elif kind == "flat_map":
                rows_out.extend(fn(row))
        return [rows_to_block(rows_out)]

    return transform


class Dataset:
    def __init__(self, plan: LogicalPlan):
        self._plan = plan

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------
    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return Dataset(self._plan.with_op(
            MapBlocks(name="Map", fn=_row_transform("map", fn))
        ))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return Dataset(self._plan.with_op(
            MapBlocks(name="Filter", fn=_row_transform("filter", fn))
        ))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return Dataset(self._plan.with_op(
            MapBlocks(name="FlatMap", fn=_row_transform("flat_map", fn))
        ))

    def map_batches(
        self,
        fn: TUnion[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: Optional[str] = None,
        compute: Optional[int] = None,  # actor pool size for class UDFs
        fn_constructor_args: tuple = (),
        concurrency: Optional[int] = None,
    ) -> "Dataset":
        batch_format = batch_format or DataContext.get_current().default_batch_format
        if isinstance(fn, type):
            pool = concurrency or compute or 2

            def actor_fn(udf, block):
                return _batch_transform(udf, batch_format, batch_size)(block)

            op = MapBlocks(
                name=f"MapBatches({fn.__name__})",
                fn=actor_fn,
                actor_cls=fn,
                actor_pool_size=pool,
                fn_args=fn_constructor_args,
            )
        else:
            op = MapBlocks(
                name="MapBatches",
                fn=_batch_transform(fn, batch_format, batch_size),
            )
        return Dataset(self._plan.with_op(op))

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(Limit(name=f"Limit[{n}]", n=n)))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(self._plan.with_op(AllToAll(
            name=f"Repartition[{num_blocks}]", kind="repartition",
            params={"num_blocks": num_blocks},
        )))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(self._plan.with_op(AllToAll(
            name="RandomShuffle", kind="random_shuffle",
            params={"seed": seed},
        )))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(self._plan.with_op(AllToAll(
            name=f"Sort[{key}]", kind="sort",
            params={"key": key, "descending": descending},
        )))

    def groupby(self, key: str) -> "GroupedDataset":
        return GroupedDataset(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(Union(
            name="Union", others=[o._plan for o in others]
        )))

    def join(self, other: "Dataset", on: str, how: str = "inner",
             right_suffix: str = "_right") -> "Dataset":
        """Hash join on a key column (reference: Dataset.join,
        data/_internal/execution/operators/join.py). how: inner|left."""
        if how not in ("inner", "left"):
            raise ValueError(f"unsupported join how={how!r}")
        return Dataset(self._plan.with_op(Join(
            name=f"Join[{on}]", other=other._plan, on=on, how=how,
            right_suffix=right_suffix,
        )))

    def zip(self, other: "Dataset") -> "Dataset":
        """Pair rows positionally; row counts must match (reference:
        Dataset.zip)."""
        return Dataset(self._plan.with_op(Zip(
            name="Zip", other=other._plan,
        )))

    # ------------------------------------------------------------------
    # column ops (map-based; reference: Dataset.add_column etc.)
    # ------------------------------------------------------------------
    def select_columns(self, cols: List[str]) -> "Dataset":
        cols = list(cols)
        return self.map(lambda r: {c: r[c] for c in cols})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map(
            lambda r: {k: v for k, v in r.items() if k not in drop})

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        return self.map(lambda r: {**r, name: fn(r)})

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map(
            lambda r: {mapping.get(k, k): v for k, v in r.items()})

    def unique(self, col: str) -> List[Any]:
        """Distinct values of a column (executes)."""
        rows = self.groupby(col).count().take_all()
        return sorted((r[col] for r in rows), key=repr)

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli sample — deterministic per row content when seeded
        (a per-task rng would resample differently on retries)."""
        import zlib

        salt = 0 if seed is None else seed

        def keep(r):
            h = zlib.crc32(repr(sorted(r.items())).encode()) ^ salt
            return (h % (1 << 20)) / float(1 << 20) < fraction

        return self.filter(keep)

    # ------------------------------------------------------------------
    # consumption (triggers execution)
    # ------------------------------------------------------------------
    def _execute(self):
        return StreamingExecutor().execute(self._plan)

    def iter_internal_refs(self):
        return self._execute()

    def iter_rows(self) -> Iterator[Any]:
        for ref, meta in self._execute():
            block = ray.get(ref, timeout=600)
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: Optional[str] = None,
        drop_last: bool = False,
    ) -> Iterator[Any]:
        batch_format = batch_format or DataContext.get_current().default_batch_format
        carry: List[Any] = []
        for ref, meta in self._execute():
            block = ray.get(ref, timeout=600)
            carry.extend(BlockAccessor.for_block(block).iter_rows())
            while len(carry) >= batch_size:
                piece = rows_to_block(carry[:batch_size])
                carry = carry[batch_size:]
                yield BlockAccessor.for_block(piece).to_batch_format(
                    batch_format
                )
        if carry and not drop_last:
            piece = rows_to_block(carry)
            yield BlockAccessor.for_block(piece).to_batch_format(batch_format)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(meta["num_rows"] for _, meta in self._execute())

    def schema(self):
        for ref, meta in self._execute():
            block = ray.get(ref, timeout=600)
            acc = BlockAccessor.for_block(block)
            if acc.num_rows() > 0:
                return acc.schema()
        return None

    def materialize(self) -> "Dataset":
        blocks = list(self._execute())
        return Dataset(LogicalPlan([InputBlocks(name="Input", blocks=blocks)]))

    def num_blocks(self) -> int:
        return len(list(self._execute()))

    def size_bytes(self) -> int:
        return sum(m["size_bytes"] for _, m in self._execute())

    # ------------------------------------------------------------------
    # splits (Train ingest; reference: _internal/split.py + streaming_split)
    # ------------------------------------------------------------------
    def split(self, n: int) -> List["Dataset"]:
        blocks = list(self.repartition(n)._execute())
        per = max(1, len(blocks) // n)
        out = []
        for i in range(n):
            chunk = blocks[i * per: (i + 1) * per] if i < n - 1 else blocks[
                (n - 1) * per:
            ]
            out.append(Dataset(LogicalPlan(
                [InputBlocks(name=f"Split[{i}]", blocks=chunk)]
            )))
        return out

    def streaming_split(self, n: int) -> List["Dataset"]:
        return self.split(n)

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------
    def write_parquet(self, path: str):
        from .datasource import write_blocks

        write_blocks(self, path, "parquet")

    def write_csv(self, path: str):
        from .datasource import write_blocks

        write_blocks(self, path, "csv")

    def write_json(self, path: str):
        from .datasource import write_blocks

        write_blocks(self, path, "json")

    def __repr__(self):
        return f"Dataset({self._plan!r})"


class GroupedDataset:
    """Reference: ray.data.grouped_data.GroupedData."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, aggs) -> Dataset:
        return Dataset(self._ds._plan.with_op(AllToAll(
            name=f"GroupBy[{self._key}]", kind="groupby",
            params={"key": self._key, "aggs": aggs},
        )))

    def count(self) -> Dataset:
        return self._agg([("count()", None, "count")])

    def sum(self, col: str) -> Dataset:
        return self._agg([(f"sum({col})", col, "sum")])

    def mean(self, col: str) -> Dataset:
        return self._agg([(f"mean({col})", col, "mean")])

    def min(self, col: str) -> Dataset:
        return self._agg([(f"min({col})", col, "min")])

    def max(self, col: str) -> Dataset:
        return self._agg([(f"max({col})", col, "max")])

    def std(self, col: str) -> Dataset:
        return self._agg([(f"std({col})", col, "std")])
