"""ray_tpu.data — distributed datasets with a streaming executor.

Reference: python/ray/data/ (SURVEY §2.4 row 1): lazy logical plan →
optimizer (map fusion) → streaming executor with bounded in-flight tasks →
Arrow blocks in the shared-memory object store.
"""
from .block import Block, BlockAccessor  # noqa: F401
from .context import DataContext  # noqa: F401
from .dataset import Dataset, GroupedDataset  # noqa: F401
from .datasource import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)
