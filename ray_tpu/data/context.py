"""DataContext: per-process execution configuration.

Reference: python/ray/data/context.py (DataContext.get_current()).
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # streaming executor: max concurrently in-flight tasks per operator
    max_tasks_in_flight: int = 8
    # rows per read task when the source has no natural partitioning
    default_read_parallelism: int = 8
    default_batch_format: str = "numpy"

    _current = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current
