"""Logical plan + optimizer.

Reference: python/ray/data/_internal/logical/ — operator DAG built lazily by
Dataset methods, optimized by rules (fusion), then planned into physical
operators. Here the same shape, compact: a linear chain of logical ops with
map-fusion (the dominant rule in the reference's optimizer).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Callable, List, Optional, Tuple

BlockTransform = Callable[[Any], List[Any]]  # block -> blocks


@dataclass
class LogicalOp:
    name: str


@dataclass
class Read(LogicalOp):
    read_tasks: List[Callable[[], List[Any]]]  # each returns block list


@dataclass
class InputBlocks(LogicalOp):
    blocks: List[Any]  # materialized blocks or (ref, meta) pairs


@dataclass
class MapBlocks(LogicalOp):
    fn: BlockTransform
    # actor-pool compute when the UDF is a stateful class (reference:
    # ActorPoolMapOperator); None = stateless tasks
    actor_cls: Optional[Any] = None
    actor_pool_size: int = 2
    fn_args: tuple = ()


@dataclass
class AllToAll(LogicalOp):
    kind: str  # repartition | random_shuffle | sort | groupby
    params: dict = field(default_factory=dict)


@dataclass
class Limit(LogicalOp):
    n: int


@dataclass
class Union(LogicalOp):
    others: List["LogicalPlan"]


@dataclass
class Join(LogicalOp):
    """Hash join against another plan (reference:
    data/_internal/execution/operators/join.py)."""

    other: "LogicalPlan"
    on: str
    how: str = "inner"  # inner | left
    right_suffix: str = "_right"


@dataclass
class Zip(LogicalOp):
    """Positional zip with another plan (reference: Dataset.zip)."""

    other: "LogicalPlan"


class LogicalPlan:
    def __init__(self, ops: List[LogicalOp]):
        self.ops = ops

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def optimized(self) -> "LogicalPlan":
        """Fuse consecutive stateless MapBlocks (reference: the
        OperatorFusionRule — avoids materializing intermediate blocks)."""
        out: List[LogicalOp] = []
        for op in self.ops:
            if (
                out
                and isinstance(op, MapBlocks)
                and isinstance(out[-1], MapBlocks)
                and op.actor_cls is None
                and out[-1].actor_cls is None
            ):
                prev = out.pop()
                f, g = prev.fn, op.fn

                def fused(block, _f=f, _g=g):
                    result = []
                    for b in _f(block):
                        result.extend(_g(b))
                    return result

                out.append(
                    MapBlocks(name=f"{prev.name}->{op.name}", fn=fused)
                )
            else:
                out.append(op)
        return LogicalPlan(out)

    def __repr__(self):
        return " -> ".join(op.name for op in self.ops)
