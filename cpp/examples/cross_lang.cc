// C++ worker example: cross-language calls + zero-copy shm objects.
//
// Usage: cross_lang <client_host> <client_port> [arena_path]
//
// 1) connects a ClientSession to the Ray Client server,
// 2) puts bytes into the cluster object store and reads them back,
// 3) invokes the Python function registered as "cpp_echo" by name,
// 4) (if arena_path given) attaches the node's shm arena through the
//    same C ABI the Python client uses and reads a sealed object
//    zero-copy.
//
// Build: g++ -std=c++17 -I cpp/include cpp/examples/cross_lang.cc -ldl
#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include "ray_tpu/client.h"

// Task-serving mode (reference: task_executor.cc): register native
// functions and execute invocations Python pushes by descriptor.
//   cross_lang <host> <port> --serve
static int ServeMode(const char* host, int port) {
  ray_tpu::TaskServer server;
  server.Register("cpp_upper", [](const std::string& payload) {
    std::string out = payload;
    for (char& c : out)
      if (c >= 'a' && c <= 'z') c = static_cast<char>(c - 'a' + 'A');
    return out;
  });
  server.Register("cpp_add1", [](const std::string& payload) {
    std::string out = payload;
    for (char& c : out) c = static_cast<char>(c + 1);
    return out;
  });
  server.Register("cpp_fail", [](const std::string&) -> std::string {
    throw std::runtime_error("native failure for the test");
  });
  // Stateful actor class (reference: RAY_REMOTE actor classes,
  // cpp/include/ray/api/actor_handle.h): a counter whose per-instance
  // state Python drives through ordered method calls.
  class Counter : public ray_tpu::CppActor {
   public:
    explicit Counter(int64_t start) : value_(start) {}
    std::string Call(const std::string& method,
                     const std::string& payload) override {
      if (method == "add") {
        unsigned char b =
            payload.empty() ? 1 : static_cast<unsigned char>(payload[0]);
        value_ += b;
        // order-sensitive digest: any reordering of add() calls
        // changes it, so the test can assert ordered execution
        digest_ = digest_ * 1000003ULL + b;
        return std::to_string(value_);
      }
      if (method == "get") return std::to_string(value_);
      if (method == "digest") return std::to_string(digest_);
      throw std::runtime_error("Counter has no method " + method);
    }

   private:
    int64_t value_;
    uint64_t digest_ = 0;
  };
  server.RegisterActorClass(
      "Counter", [](const std::string& init) {
        int64_t start = init.empty() ? 0 : std::stoll(init);
        return std::unique_ptr<ray_tpu::CppActor>(new Counter(start));
      });
  int bound = server.Listen("127.0.0.1", 0);
  ray_tpu::ClientSession sess(host, port);
  sess.RegisterCppWorker(server.FunctionNames(), "127.0.0.1", bound);
  std::printf("CPP_SERVING %d\n", bound);
  std::fflush(stdout);
  server.ServeForever();
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s host port [--serve | arena_path [lib_path]]\n",
                 argv[0]);
    return 2;
  }
  if (argc >= 4 && std::string(argv[3]) == "--serve")
    return ServeMode(argv[1], std::atoi(argv[2]));
  ray_tpu::ClientSession sess(argv[1], std::atoi(argv[2]));
  std::printf("session: %s\n", sess.session_id().c_str());

  // object round-trip through the cluster store
  std::string ref = sess.PutBytes("hello from c++");
  std::string back = sess.GetBytes(ref);
  std::printf("put/get: %s\n", back.c_str());
  if (back != "hello from c++") return 1;

  // cross-language call by name
  std::string out_ref = sess.CallNamed("cpp_echo", "ping-42");
  std::string result = sess.GetBytes(out_ref, 60.0);
  std::printf("cpp_echo -> %s\n", result.c_str());
  if (result != "echo:ping-42") return 1;

  // cluster info through the same session
  ray_tpu::Value nodes = sess.Api("nodes");
  std::printf("nodes: %zu\n", nodes.as_list().size());

  if (argc >= 5) {
    // zero-copy read from the node's shm arena: Python seals an object
    // with a known 20-byte id ("cpp_interop_test\0\0\0\0"); we attach
    // the arena and map the payload directly.
    void* lib = dlopen(argv[4], RTLD_NOW);
    if (!lib) {
      std::fprintf(stderr, "dlopen failed: %s\n", dlerror());
      return 1;
    }
    using OpenFn = void* (*)(const char*, uint64_t, int);
    using BaseFn = uint64_t (*)(void*);
    using GetFn = int (*)(void*, const uint8_t*, int64_t, uint64_t*,
                          uint64_t*);
    auto open_fn = reinterpret_cast<OpenFn>(dlsym(lib, "shm_store_open"));
    auto base_fn = reinterpret_cast<BaseFn>(dlsym(lib, "shm_store_base"));
    auto get_fn = reinterpret_cast<GetFn>(dlsym(lib, "shm_store_get"));
    void* store = open_fn(argv[3], 0, 0);
    if (!store) {
      std::fprintf(stderr, "arena attach failed\n");
      return 1;
    }
    uint8_t id[20] = {0};
    std::memcpy(id, "cpp_interop_test", 16);
    uint64_t off = 0, size = 0;
    if (get_fn(store, id, 10000, &off, &size) != 0) {
      std::fprintf(stderr, "shm get failed\n");
      return 1;
    }
    const char* payload =
        reinterpret_cast<const char*>(base_fn(store)) + off;
    std::printf("shm object (%llu bytes): %.*s\n",
                (unsigned long long)size, (int)size, payload);
    if (std::string(payload, size) != "zero-copy-from-python") return 1;
  }
  std::printf("CPP_WORKER_OK\n");
  return 0;
}
