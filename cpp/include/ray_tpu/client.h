// C++ worker API: a native client for a ray_tpu cluster.
//
// Reference: cpp/include/ray/api.h — the reference's C++ worker links
// libcoreworker and drives gRPC. Here the native client speaks the
// framework's own RPC framing (8-byte LE length + pickle, see
// _private/rpc.py) against the Ray Client server (util/client/server.py),
// which hosts per-session proxy state; cross-language calls go through
// the by-name function registry (ray_tpu/cross_language.py) with bytes
// payloads — the same function-descriptor-by-name shape the reference
// uses for cross-language invocation (python/ray/cross_language.py).
//
// The OBJECT plane needs no RPC at all: link libshmstore.so (the same
// C ABI the Python client binds with ctypes) to read/write the node's
// shared-memory arena zero-copy. See examples/cross_lang.cc.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>

#include "pickle.h"

namespace ray_tpu {

class RpcError : public std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Blocking single-connection RPC client (one in-flight call at a time;
// the server replies per-seq so pipelining is possible, but the C++
// worker API keeps the surface synchronous like the reference's).
class RpcClient {
 public:
  RpcClient(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw RpcError("socket() failed");
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw RpcError("bad address: " + host);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw RpcError("connect() failed to " + host);
  }
  ~RpcClient() {
    if (fd_ >= 0) ::close(fd_);
  }
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  Value Call(const std::string& method, const ValueDict& kwargs) {
    std::lock_guard<std::mutex> lock(mu_);
    int64_t seq = next_seq_++;
    std::string payload = pickle::EncodeCall(seq, method, kwargs);
    char hdr[8];
    uint64_t n = payload.size();
    std::memcpy(hdr, &n, 8);
    WriteAll(hdr, 8);
    WriteAll(payload.data(), payload.size());
    // read frames until our seq answers (the server may interleave)
    for (;;) {
      char rhdr[8];
      ReadAll(rhdr, 8);
      uint64_t rn;
      std::memcpy(&rn, rhdr, 8);
      std::string data(rn, '\0');
      ReadAll(data.data(), rn);
      Value frame = pickle::Decode(data);
      const ValueList& tup = frame.as_list();  // (seq, status, result)
      if (tup.at(0).as_int() != seq) continue;
      if (tup.at(1).as_int() != 0)
        throw RpcError("remote error: " + tup.at(2).as_str());
      return tup.at(2);
    }
  }

 private:
  void WriteAll(const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = ::write(fd_, p, n);
      if (w <= 0) throw RpcError("write() failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void ReadAll(char* p, size_t n) {
    while (n > 0) {
      ssize_t r = ::read(fd_, p, n);
      if (r <= 0) throw RpcError("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  int64_t next_seq_ = 1;
  std::mutex mu_;
};

// One session against the Ray Client server: put/get objects, call
// registered cross-language functions, query cluster state.
class ClientSession {
 public:
  ClientSession(const std::string& host, int port) : rpc_(host, port) {
    Value res = rpc_.Call("client_connect", {{"namespace", Value("")}});
    session_id_ = res.at("session_id").as_str();
  }
  ~ClientSession() {
    try {
      rpc_.Call("client_disconnect", WithSession({}));
    } catch (...) {
    }
  }

  // Store bytes in the cluster object store; returns the ref id.
  std::string PutBytes(const std::string& data) {
    Value res = rpc_.Call(
        "client_put_bytes", WithSession({{"payload", Value::Bytes(data)}}));
    return res.as_str();
  }

  // Fetch an object produced by a cross-language call (bytes out).
  std::string GetBytes(const std::string& ref_id, double timeout_s = 60.0) {
    Value res = rpc_.Call(
        "client_get_bytes",
        WithSession({{"ref_id", Value(ref_id)},
                     {"get_timeout", Value(timeout_s)}}));
    return res.as_bytes();
  }

  // Invoke a Python function registered via
  // ray_tpu.cross_language.register_function(name, fn); the function
  // receives the payload bytes and must return bytes. Returns a ref id.
  std::string CallNamed(const std::string& func_name,
                        const std::string& payload) {
    Value res = rpc_.Call(
        "client_task_by_name",
        WithSession({{"func_name", Value(func_name)},
                     {"payload", Value::Bytes(payload)}}));
    return res.as_str();
  }

  // Cluster info passthrough ("nodes", "cluster_resources", ...).
  Value Api(const std::string& method) {
    return rpc_.Call("client_api",
                     WithSession({{"api_method", Value(method)}}));
  }

  // Announce a C++ task server: Python resolves these functions by
  // descriptor (cross_language.cpp_function) and pushes invocations to
  // host:port.
  void RegisterCppWorker(const ValueList& function_names,
                         const std::string& host, int port) {
    ValueDict kw;
    kw["functions"] = Value(function_names);
    kw["host"] = Value(host);
    kw["port"] = Value(static_cast<int64_t>(port));
    rpc_.Call("client_register_cpp_worker", WithSession(std::move(kw)));
  }

  const std::string& session_id() const { return session_id_; }

 private:
  ValueDict WithSession(ValueDict kwargs) {
    kwargs["session_id"] = Value(session_id_);
    return kwargs;
  }

  RpcClient rpc_;
  std::string session_id_;
};

// ---------------------------------------------------------------------------
// Task-serving mode: the C++ worker REGISTERS functions and executes
// tasks Python pushes by descriptor.
//
// Reference: cpp/src/ray/runtime/task/task_executor.cc — the reference
// C++ worker's executor loop receives pushed tasks and dispatches to
// statically-registered functions (RAY_REMOTE). Here the server speaks
// the framework's own (seq, method, kwargs) framing, so any cluster
// process (including Python task executors resolving
// cross_language.cpp_function descriptors) can push invocations with
// the standard RpcClient pool.
// ---------------------------------------------------------------------------
// Base class for C++-hosted actors (reference:
// cpp/include/ray/api/actor_handle.h + actor_creator.h — RAY_REMOTE
// actor classes instantiated and driven by the runtime). Subclasses
// dispatch by method name; per-instance state lives in the object, and
// the TaskServer executes an instance's methods one at a time in
// arrival order (Python's actor machinery provides the per-caller
// submission ordering, like any other actor).
class CppActor {
 public:
  virtual ~CppActor() = default;
  // method name + payload bytes in, reply bytes out
  virtual std::string Call(const std::string& method,
                           const std::string& payload) = 0;
};

class TaskServer {
 public:
  using Fn = std::function<std::string(const std::string&)>;
  using ActorFactory =
      std::function<std::unique_ptr<CppActor>(const std::string&)>;

  void Register(const std::string& name, Fn fn) {
    fns_[name] = std::move(fn);
  }

  // Register an actor CLASS: Python creates instances by descriptor
  // ("actor:<name>") with an init payload; the factory returns the
  // instance this server then hosts.
  void RegisterActorClass(const std::string& name, ActorFactory factory) {
    actor_factories_[name] = std::move(factory);
  }

  ValueList FunctionNames() const {
    ValueList out;
    for (const auto& [name, _fn] : fns_) out.push_back(Value(name));
    for (const auto& [name, _f] : actor_factories_)
      out.push_back(Value("actor:" + name));
    return out;
  }

  // Bind + listen; returns the bound port (0 = ephemeral).
  int Listen(const std::string& host = "127.0.0.1", int port = 0) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) throw RpcError("socket() failed");
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw RpcError("bad address: " + host);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      throw RpcError("bind() failed");
    if (::listen(listen_fd_, 16) != 0) throw RpcError("listen() failed");
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    return ntohs(addr.sin_port);
  }

  // Accept loop; each connection is served on its own thread (Python
  // keeps one pooled connection per process and pipelines frames).
  // Runs until the process exits.
  void ServeForever() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) continue;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::thread([this, fd] { ServeConnection(fd); }).detach();
    }
  }

 private:
  void ServeConnection(int fd) {
    try {
      for (;;) {
        char hdr[8];
        if (!ReadAllFd(fd, hdr, 8)) break;
        uint64_t n;
        std::memcpy(&n, hdr, 8);
        std::string data(n, '\0');
        if (!ReadAllFd(fd, data.data(), n)) break;
        Value frame = pickle::Decode(data);
        const ValueList& tup = frame.as_list();  // (seq, method, kwargs)
        int64_t seq = tup.at(0).as_int();
        const std::string& method = tup.at(1).as_str();
        std::string reply;
        if (method == "ping") {
          reply = pickle::EncodeReply(seq, 0, Value(true));
        } else if (method == "invoke_cpp") {
          const ValueDict& kw = tup.at(2).as_dict();
          const std::string& fn_name = kw.at("fn").as_str();
          auto it = fns_.find(fn_name);
          if (it == fns_.end()) {
            reply = pickle::EncodeReply(
                seq, 1, Value("KeyError: no C++ function " + fn_name));
          } else {
            try {
              std::string out = it->second(kw.at("payload").as_bytes());
              reply = pickle::EncodeReply(seq, 0,
                                          Value::Bytes(std::move(out)));
            } catch (const std::exception& e) {
              reply = pickle::EncodeReply(
                  seq, 1, Value(std::string("RuntimeError: ") + e.what()));
            }
          }
        } else if (method == "create_cpp_actor") {
          const ValueDict& kw = tup.at(2).as_dict();
          const std::string& cls = kw.at("cls").as_str();
          const std::string& actor_id = kw.at("actor_id").as_str();
          auto it = actor_factories_.find(cls);
          if (it == actor_factories_.end()) {
            reply = pickle::EncodeReply(
                seq, 1, Value("KeyError: no C++ actor class " + cls));
          } else {
            try {
              auto inst = it->second(kw.at("payload").as_bytes());
              {
                std::lock_guard<std::mutex> lock(actors_mu_);
                actors_[actor_id] =
                    std::make_shared<ActorSlot>(std::move(inst));
              }
              reply = pickle::EncodeReply(seq, 0, Value(true));
            } catch (const std::exception& e) {
              reply = pickle::EncodeReply(
                  seq, 1, Value(std::string("RuntimeError: ") + e.what()));
            }
          }
        } else if (method == "invoke_cpp_actor") {
          const ValueDict& kw = tup.at(2).as_dict();
          const std::string& actor_id = kw.at("actor_id").as_str();
          std::shared_ptr<ActorSlot> slot;
          {
            std::lock_guard<std::mutex> lock(actors_mu_);
            auto it = actors_.find(actor_id);
            if (it != actors_.end()) slot = it->second;
          }
          if (!slot) {
            reply = pickle::EncodeReply(
                seq, 1, Value("KeyError: no C++ actor " + actor_id));
          } else {
            try {
              // per-instance serialization: methods of one actor run
              // one at a time, in arrival order
              std::lock_guard<std::mutex> lock(slot->mu);
              std::string out = slot->actor->Call(
                  kw.at("actor_method").as_str(), kw.at("payload").as_bytes());
              reply = pickle::EncodeReply(seq, 0,
                                          Value::Bytes(std::move(out)));
            } catch (const std::exception& e) {
              reply = pickle::EncodeReply(
                  seq, 1, Value(std::string("RuntimeError: ") + e.what()));
            }
          }
        } else if (method == "destroy_cpp_actor") {
          const ValueDict& kw = tup.at(2).as_dict();
          std::lock_guard<std::mutex> lock(actors_mu_);
          actors_.erase(kw.at("actor_id").as_str());
          reply = pickle::EncodeReply(seq, 0, Value(true));
        } else {
          reply = pickle::EncodeReply(seq, 1,
                                      Value("no such method: " + method));
        }
        char rhdr[8];
        uint64_t rn = reply.size();
        std::memcpy(rhdr, &rn, 8);
        if (!WriteAllFd(fd, rhdr, 8)) break;
        if (!WriteAllFd(fd, reply.data(), reply.size())) break;
      }
    } catch (...) {
    }
    ::close(fd);
  }

  static bool ReadAllFd(int fd, char* p, size_t n) {
    while (n > 0) {
      ssize_t r = ::read(fd, p, n);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }
  static bool WriteAllFd(int fd, const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = ::write(fd, p, n);
      if (w <= 0) return false;
      p += w;
      n -= static_cast<size_t>(w);
    }
    return true;
  }

  struct ActorSlot {
    explicit ActorSlot(std::unique_ptr<CppActor> a) : actor(std::move(a)) {}
    std::unique_ptr<CppActor> actor;
    std::mutex mu;  // serializes this instance's methods
  };

  std::map<std::string, Fn> fns_;
  std::map<std::string, ActorFactory> actor_factories_;
  std::map<std::string, std::shared_ptr<ActorSlot>> actors_;
  std::mutex actors_mu_;
  int listen_fd_ = -1;
};

}  // namespace ray_tpu
