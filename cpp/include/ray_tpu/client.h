// C++ worker API: a native client for a ray_tpu cluster.
//
// Reference: cpp/include/ray/api.h — the reference's C++ worker links
// libcoreworker and drives gRPC. Here the native client speaks the
// framework's own RPC framing (8-byte LE length + pickle, see
// _private/rpc.py) against the Ray Client server (util/client/server.py),
// which hosts per-session proxy state; cross-language calls go through
// the by-name function registry (ray_tpu/cross_language.py) with bytes
// payloads — the same function-descriptor-by-name shape the reference
// uses for cross-language invocation (python/ray/cross_language.py).
//
// The OBJECT plane needs no RPC at all: link libshmstore.so (the same
// C ABI the Python client binds with ctypes) to read/write the node's
// shared-memory arena zero-copy. See examples/cross_lang.cc.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <mutex>
#include <stdexcept>
#include <string>

#include "pickle.h"

namespace ray_tpu {

class RpcError : public std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Blocking single-connection RPC client (one in-flight call at a time;
// the server replies per-seq so pipelining is possible, but the C++
// worker API keeps the surface synchronous like the reference's).
class RpcClient {
 public:
  RpcClient(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw RpcError("socket() failed");
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw RpcError("bad address: " + host);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw RpcError("connect() failed to " + host);
  }
  ~RpcClient() {
    if (fd_ >= 0) ::close(fd_);
  }
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  Value Call(const std::string& method, const ValueDict& kwargs) {
    std::lock_guard<std::mutex> lock(mu_);
    int64_t seq = next_seq_++;
    std::string payload = pickle::EncodeCall(seq, method, kwargs);
    char hdr[8];
    uint64_t n = payload.size();
    std::memcpy(hdr, &n, 8);
    WriteAll(hdr, 8);
    WriteAll(payload.data(), payload.size());
    // read frames until our seq answers (the server may interleave)
    for (;;) {
      char rhdr[8];
      ReadAll(rhdr, 8);
      uint64_t rn;
      std::memcpy(&rn, rhdr, 8);
      std::string data(rn, '\0');
      ReadAll(data.data(), rn);
      Value frame = pickle::Decode(data);
      const ValueList& tup = frame.as_list();  // (seq, status, result)
      if (tup.at(0).as_int() != seq) continue;
      if (tup.at(1).as_int() != 0)
        throw RpcError("remote error: " + tup.at(2).as_str());
      return tup.at(2);
    }
  }

 private:
  void WriteAll(const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = ::write(fd_, p, n);
      if (w <= 0) throw RpcError("write() failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void ReadAll(char* p, size_t n) {
    while (n > 0) {
      ssize_t r = ::read(fd_, p, n);
      if (r <= 0) throw RpcError("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  int64_t next_seq_ = 1;
  std::mutex mu_;
};

// One session against the Ray Client server: put/get objects, call
// registered cross-language functions, query cluster state.
class ClientSession {
 public:
  ClientSession(const std::string& host, int port) : rpc_(host, port) {
    Value res = rpc_.Call("client_connect", {{"namespace", Value("")}});
    session_id_ = res.at("session_id").as_str();
  }
  ~ClientSession() {
    try {
      rpc_.Call("client_disconnect", WithSession({}));
    } catch (...) {
    }
  }

  // Store bytes in the cluster object store; returns the ref id.
  std::string PutBytes(const std::string& data) {
    Value res = rpc_.Call(
        "client_put_bytes", WithSession({{"payload", Value::Bytes(data)}}));
    return res.as_str();
  }

  // Fetch an object produced by a cross-language call (bytes out).
  std::string GetBytes(const std::string& ref_id, double timeout_s = 60.0) {
    Value res = rpc_.Call(
        "client_get_bytes",
        WithSession({{"ref_id", Value(ref_id)},
                     {"get_timeout", Value(timeout_s)}}));
    return res.as_bytes();
  }

  // Invoke a Python function registered via
  // ray_tpu.cross_language.register_function(name, fn); the function
  // receives the payload bytes and must return bytes. Returns a ref id.
  std::string CallNamed(const std::string& func_name,
                        const std::string& payload) {
    Value res = rpc_.Call(
        "client_task_by_name",
        WithSession({{"func_name", Value(func_name)},
                     {"payload", Value::Bytes(payload)}}));
    return res.as_str();
  }

  // Cluster info passthrough ("nodes", "cluster_resources", ...).
  Value Api(const std::string& method) {
    return rpc_.Call("client_api",
                     WithSession({{"api_method", Value(method)}}));
  }

  const std::string& session_id() const { return session_id_; }

 private:
  ValueDict WithSession(ValueDict kwargs) {
    kwargs["session_id"] = Value(session_id_);
    return kwargs;
  }

  RpcClient rpc_;
  std::string session_id_;
};

}  // namespace ray_tpu
