// Pickle-subset codec for the ray_tpu RPC wire format.
//
// Reference analogue: cpp/include/ray/api/serializer.h — the reference's
// C++ worker serializes with msgpack because its transport is gRPC;
// here the transport frames are Python pickles of plain
// (seq, method, kwargs) tuples, so the C++ worker speaks exactly the
// value subset both ends actually use: None, bool, int, float, str,
// bytes, list, tuple, dict[str->value].
//
// Encoder emits protocol 2 (universally loadable); decoder handles the
// opcodes CPython's protocol-5 pickler produces for this subset.
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {

class Value;
using ValueList = std::vector<Value>;
using ValueDict = std::map<std::string, Value>;

class Value {
 public:
  enum class Kind { kNone, kBool, kInt, kFloat, kStr, kBytes, kList, kDict };

  Value() : kind_(Kind::kNone) {}
  Value(bool b) : kind_(Kind::kBool), int_(b ? 1 : 0) {}
  Value(int64_t i) : kind_(Kind::kInt), int_(i) {}
  Value(int i) : kind_(Kind::kInt), int_(i) {}
  Value(double d) : kind_(Kind::kFloat), float_(d) {}
  Value(const char* s) : kind_(Kind::kStr), str_(s) {}
  Value(std::string s) : kind_(Kind::kStr), str_(std::move(s)) {}
  static Value Bytes(std::string b) {
    Value v;
    v.kind_ = Kind::kBytes;
    v.str_ = std::move(b);
    return v;
  }
  Value(ValueList l) : kind_(Kind::kList), list_(std::move(l)) {}
  Value(ValueDict d) : kind_(Kind::kDict), dict_(std::move(d)) {}

  Kind kind() const { return kind_; }
  bool is_none() const { return kind_ == Kind::kNone; }
  bool as_bool() const { return int_ != 0; }
  int64_t as_int() const { return int_; }
  double as_float() const {
    return kind_ == Kind::kInt ? static_cast<double>(int_) : float_;
  }
  const std::string& as_str() const { return str_; }
  const std::string& as_bytes() const { return str_; }
  const ValueList& as_list() const { return list_; }
  const ValueDict& as_dict() const { return dict_; }
  const Value& at(const std::string& key) const { return dict_.at(key); }

 private:
  Kind kind_;
  int64_t int_ = 0;
  double float_ = 0.0;
  std::string str_;
  ValueList list_;
  ValueDict dict_;
};

namespace pickle {

// ---------------------------------------------------------------- encode
inline void PutU32(std::string& out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);  // little-endian hosts only (x86/arm64)
  out.append(b, 4);
}

inline void Encode(const Value& v, std::string& out);

inline void EncodeStr(const std::string& s, std::string& out) {
  out.push_back('X');  // BINUNICODE
  PutU32(out, static_cast<uint32_t>(s.size()));
  out += s;
}

inline void Encode(const Value& v, std::string& out) {
  switch (v.kind()) {
    case Value::Kind::kNone:
      out.push_back('N');
      break;
    case Value::Kind::kBool:
      out += v.as_bool() ? "\x88" : "\x89";  // NEWTRUE / NEWFALSE
      break;
    case Value::Kind::kInt: {
      int64_t i = v.as_int();
      if (i >= 0 && i < (1LL << 31)) {
        out.push_back('J');  // BININT (signed 4-byte)
        PutU32(out, static_cast<uint32_t>(i));
      } else {
        out += "\x8a\x08";  // LONG1, 8 bytes
        char b[8];
        std::memcpy(b, &i, 8);
        out.append(b, 8);
      }
      break;
    }
    case Value::Kind::kFloat: {
      out.push_back('G');  // BINFLOAT (big-endian IEEE754)
      double d = v.as_float();
      uint64_t u;
      std::memcpy(&u, &d, 8);
      for (int i = 7; i >= 0; --i)
        out.push_back(static_cast<char>((u >> (i * 8)) & 0xff));
      break;
    }
    case Value::Kind::kStr:
      EncodeStr(v.as_str(), out);
      break;
    case Value::Kind::kBytes: {
      const std::string& b = v.as_bytes();
      out.push_back('B');  // BINBYTES
      PutU32(out, static_cast<uint32_t>(b.size()));
      out += b;
      break;
    }
    case Value::Kind::kList: {
      out.push_back(']');  // EMPTY_LIST
      out.push_back('(');  // MARK
      for (const auto& item : v.as_list()) Encode(item, out);
      out.push_back('e');  // APPENDS
      break;
    }
    case Value::Kind::kDict: {
      out.push_back('}');  // EMPTY_DICT
      out.push_back('(');  // MARK
      for (const auto& [k, val] : v.as_dict()) {
        EncodeStr(k, out);
        Encode(val, out);
      }
      out.push_back('u');  // SETITEMS
      break;
    }
  }
}

// Encodes the request frame payload: the (seq, method, kwargs) tuple.
inline std::string EncodeCall(int64_t seq, const std::string& method,
                              const ValueDict& kwargs) {
  std::string out("\x80\x02", 2);  // PROTO 2
  Value seq_v(seq);
  Encode(seq_v, out);
  EncodeStr(method, out);
  Encode(Value(kwargs), out);
  out += "\x87";  // TUPLE3
  out.push_back('.');  // STOP
  return out;
}

// Encodes a reply frame payload: the (seq, status, result) tuple the
// Python RpcClient expects back (rpc.py _dispatch reply shape).
inline std::string EncodeReply(int64_t seq, int64_t status,
                               const Value& result) {
  std::string out("\x80\x02", 2);  // PROTO 2
  Encode(Value(seq), out);
  Encode(Value(status), out);
  Encode(result, out);
  out += "\x87";  // TUPLE3
  out.push_back('.');  // STOP
  return out;
}

// ---------------------------------------------------------------- decode
class Decoder {
 public:
  explicit Decoder(const std::string& data) : d_(data) {}

  Value Parse() {
    while (pos_ < d_.size()) {
      unsigned char op = Next();
      switch (op) {
        case 0x80:  // PROTO
          Next();
          break;
        case 0x95:  // FRAME
          pos_ += 8;
          break;
        case '.':  // STOP
          if (stack_.empty()) throw std::runtime_error("pickle: empty");
          return Top();
        case 'N':
          Push(Value());
          break;
        case 0x88:
          Push(Value(true));
          break;
        case 0x89:
          Push(Value(false));
          break;
        case 'K':  // BININT1
          Push(Value(static_cast<int64_t>(Next())));
          break;
        case 'M': {  // BININT2
          uint16_t v = Next();
          v |= static_cast<uint16_t>(Next()) << 8;
          Push(Value(static_cast<int64_t>(v)));
          break;
        }
        case 'J': {  // BININT
          int32_t v;
          ReadRaw(&v, 4);
          Push(Value(static_cast<int64_t>(v)));
          break;
        }
        case 0x8a: {  // LONG1
          unsigned char n = Next();
          if (n > 8) throw std::runtime_error("pickle: LONG1 too wide");
          int64_t v = 0;
          unsigned char last = 0;
          for (int i = 0; i < n; ++i) {
            last = Next();
            v |= static_cast<int64_t>(last) << (i * 8);
          }
          if (n > 0 && n < 8 && (last & 0x80))  // sign-extend
            v -= (1LL << (n * 8));
          Push(Value(v));
          break;
        }
        case 'G': {  // BINFLOAT (big-endian)
          uint64_t u = 0;
          for (int i = 0; i < 8; ++i) u = (u << 8) | Next();
          double dv;
          std::memcpy(&dv, &u, 8);
          Push(Value(dv));
          break;
        }
        case 0x8c: {  // SHORT_BINUNICODE
          unsigned char n = Next();
          Push(Value(ReadStr(n)));
          break;
        }
        case 'X': {  // BINUNICODE
          uint32_t n;
          ReadRaw(&n, 4);
          Push(Value(ReadStr(n)));
          break;
        }
        case 'C': {  // SHORT_BINBYTES
          unsigned char n = Next();
          Push(Value::Bytes(ReadStr(n)));
          break;
        }
        case 'B': {  // BINBYTES
          uint32_t n;
          ReadRaw(&n, 4);
          Push(Value::Bytes(ReadStr(n)));
          break;
        }
        case 0x8e: {  // BINBYTES8
          uint64_t n;
          ReadRaw(&n, 8);
          Push(Value::Bytes(ReadStr(n)));
          break;
        }
        case 0x94:  // MEMOIZE (implicit next index)
          memo_.push_back(Top());
          break;
        case 'q': {  // BINPUT
          size_t i = Next();
          if (memo_.size() <= i) memo_.resize(i + 1);
          memo_[i] = Top();
          break;
        }
        case 'r': {  // LONG_BINPUT
          uint32_t i;
          ReadRaw(&i, 4);
          if (memo_.size() <= i) memo_.resize(i + 1);
          memo_[i] = Top();
          break;
        }
        case 'h':  // BINGET
          Push(memo_.at(Next()));
          break;
        case 'j': {  // LONG_BINGET
          uint32_t i;
          ReadRaw(&i, 4);
          Push(memo_.at(i));
          break;
        }
        case '(':  // MARK
          marks_.push_back(stack_.size());
          break;
        case ']':  // EMPTY_LIST
          Push(Value(ValueList{}));
          break;
        case '}':  // EMPTY_DICT
          Push(Value(ValueDict{}));
          break;
        case 'a': {  // APPEND (single)
          Value item = Pop();
          ValueList base = Top().as_list();
          stack_.pop_back();
          base.push_back(std::move(item));
          Push(Value(std::move(base)));
          break;
        }
        case 'e': {  // APPENDS
          size_t m = PopMark();
          ValueList items(stack_.begin() + m, stack_.end());
          stack_.resize(m);
          ValueList base = Top().as_list();
          stack_.pop_back();
          for (auto& it : items) base.push_back(std::move(it));
          Push(Value(std::move(base)));
          break;
        }
        case 'u': {  // SETITEMS
          size_t m = PopMark();
          ValueDict d = MakeDict(m);
          ValueDict base = Top().as_dict();
          stack_.pop_back();
          for (auto& [k, val] : d) base[k] = std::move(val);
          Push(Value(std::move(base)));
          break;
        }
        case 's': {  // SETITEM
          Value val = Pop();
          Value key = Pop();
          ValueDict base = Top().as_dict();
          stack_.pop_back();
          base[key.as_str()] = std::move(val);
          Push(Value(std::move(base)));
          break;
        }
        case 0x85: {  // TUPLE1 (as list)
          Value a = Pop();
          Push(Value(ValueList{std::move(a)}));
          break;
        }
        case 0x86: {  // TUPLE2
          Value b = Pop(), a = Pop();
          Push(Value(ValueList{std::move(a), std::move(b)}));
          break;
        }
        case 0x87: {  // TUPLE3
          Value c = Pop(), b = Pop(), a = Pop();
          Push(Value(ValueList{std::move(a), std::move(b), std::move(c)}));
          break;
        }
        case 't': {  // TUPLE (from mark)
          size_t m = PopMark();
          ValueList items(stack_.begin() + m, stack_.end());
          stack_.resize(m);
          Push(Value(std::move(items)));
          break;
        }
        case ')':  // EMPTY_TUPLE
          Push(Value(ValueList{}));
          break;
        default:
          throw std::runtime_error("pickle: unsupported opcode " +
                                   std::to_string(op));
      }
    }
    throw std::runtime_error("pickle: no STOP");
  }

 private:
  unsigned char Next() {
    if (pos_ >= d_.size()) throw std::runtime_error("pickle: truncated");
    return static_cast<unsigned char>(d_[pos_++]);
  }
  void ReadRaw(void* dst, size_t n) {
    if (pos_ + n > d_.size()) throw std::runtime_error("pickle: truncated");
    std::memcpy(dst, d_.data() + pos_, n);
    pos_ += n;
  }
  std::string ReadStr(uint64_t n) {
    if (pos_ + n > d_.size()) throw std::runtime_error("pickle: truncated");
    std::string s = d_.substr(pos_, n);
    pos_ += n;
    return s;
  }
  void Push(Value v) { stack_.push_back(std::move(v)); }
  // All stack/mark accesses are underflow-checked so a truncated or
  // corrupt frame raises std::runtime_error instead of hitting UB on an
  // empty container (malformed input must fail loudly, not crash).
  Value& Top() {
    if (stack_.empty()) throw std::runtime_error("pickle: stack underflow");
    return stack_.back();
  }
  Value Pop() {
    if (stack_.empty()) throw std::runtime_error("pickle: stack underflow");
    Value v = std::move(stack_.back());
    stack_.pop_back();
    return v;
  }
  size_t PopMark() {
    if (marks_.empty()) throw std::runtime_error("pickle: mark underflow");
    size_t m = marks_.back();
    marks_.pop_back();
    if (m > stack_.size())
      throw std::runtime_error("pickle: mark beyond stack");
    return m;
  }
  ValueDict MakeDict(size_t from) {
    ValueDict d;
    for (size_t i = from; i + 1 < stack_.size(); i += 2)
      d[stack_[i].as_str()] = stack_[i + 1];
    stack_.resize(from);
    return d;
  }

  const std::string& d_;
  size_t pos_ = 0;
  ValueList stack_;
  ValueList memo_;
  std::vector<size_t> marks_;
};

inline Value Decode(const std::string& data) { return Decoder(data).Parse(); }

}  // namespace pickle
}  // namespace ray_tpu
